"""Ragged paged attention (ISSUE 7): one kernel + token-budget scheduler
for true continuous batching — kernel parity vs the dense reference
across ragged descriptor layouts, and engine acceptance that greedy
outputs under the ragged scheduler stay bit-identical to the legacy
two-program path and the dense oracle (incl. prefix-cache hits and
cancellation)."""
import threading
import time

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import ContinuousServingEngine
from paddle_tpu.models import LlamaForCausalLM, llama_tiny
from paddle_tpu.ops.pallas.ragged_paged_attention import (
    ragged_paged_attention, ragged_paged_attention_reference,
    _ragged_paged_attention_xla, _token_descriptors)


# ---------------------------------------------------------------------------
# kernel parity vs the dense reference, across descriptor layouts
# ---------------------------------------------------------------------------

def _pool(nslots=4, pages_per_seq=4, page=8, kv_heads=2, d=32, seed=0):
    rng = np.random.RandomState(seed)
    npages = nslots * pages_per_seq + 1          # page 0 = scratch
    kp = jnp.asarray(rng.randn(kv_heads, npages, page, d), jnp.float32)
    vp = jnp.asarray(rng.randn(kv_heads, npages, page, d), jnp.float32)
    tbl = np.zeros((nslots, pages_per_seq), np.int32)
    for s in range(nslots):
        tbl[s] = np.arange(1 + s * pages_per_seq,
                           1 + (s + 1) * pages_per_seq)
    return kp, vp, tbl


def _check(layout, nslots=4, heads=4, d=32, seed=0, tokens=None):
    """layout: list of (slot, q_start, q_len, context_len)."""
    kp, vp, tbl = _pool(nslots=nslots, d=d, seed=seed)
    seq_slots = np.asarray([x[0] for x in layout], np.int32)
    q_starts = np.asarray([x[1] for x in layout], np.int32)
    q_lens = np.asarray([x[2] for x in layout], np.int32)
    ctx = np.asarray([x[3] for x in layout], np.int32)
    T = tokens or int((q_starts + q_lens).max())
    rng = np.random.RandomState(seed + 1)
    q = jnp.asarray(rng.randn(T, heads, d), jnp.float32)
    ref = np.asarray(ragged_paged_attention_reference(
        q, kp, vp, tbl, seq_slots, q_starts, q_lens, ctx))
    out = np.asarray(ragged_paged_attention(
        q, kp, vp, jnp.asarray(tbl), seq_slots, q_starts, q_lens, ctx,
        interpret=True))
    ts, tc = _token_descriptors(T, seq_slots, q_starts, q_lens, ctx)
    xla = np.asarray(_ragged_paged_attention_xla(
        q, kp, vp, jnp.asarray(tbl), ts, tc, sm_scale=d ** -0.5))
    for slot, qs, ql, _ in layout:               # pad rows are garbage
        np.testing.assert_allclose(out[qs:qs + ql], ref[qs:qs + ql],
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(xla[qs:qs + ql], ref[qs:qs + ql],
                                   rtol=2e-5, atol=2e-5)
    return out, ref


def test_kernel_pure_decode():
    # every span is one token — the continuous-batching steady state
    _check([(0, 0, 1, 7), (1, 1, 1, 19), (2, 2, 1, 32), (3, 3, 1, 1)])


def test_kernel_pure_prefill():
    _check([(0, 0, 9, 9), (1, 9, 14, 14), (2, 23, 5, 5)])


def test_kernel_mixed_prefill_decode_with_padding():
    # decode tokens + chunked-prefill continuation (context > q_len) +
    # bucket padding at the tail (tokens=32 > last span end)
    _check([(0, 0, 1, 12), (1, 1, 1, 25), (2, 2, 11, 18), (3, 13, 6, 6)],
           tokens=32)


def test_kernel_single_token_tail():
    # a prefill span of exactly 1 token (prompt tail after a prefix-cache
    # hit) must behave like decode with its own context
    _check([(0, 0, 1, 17), (1, 1, 1, 8)])


def test_kernel_shared_prefix_pages():
    """Two slots whose block tables alias the same leading pages (a
    prefix-cache hit): outputs must match a reference reading through
    the same aliased tables."""
    kp, vp, tbl = _pool(nslots=2, pages_per_seq=4)
    tbl[1, :2] = tbl[0, :2]                      # shared 16-token prefix
    layout = [(0, 0, 1, 20), (1, 1, 3, 19)]
    seq_slots = np.asarray([x[0] for x in layout], np.int32)
    q_starts = np.asarray([x[1] for x in layout], np.int32)
    q_lens = np.asarray([x[2] for x in layout], np.int32)
    ctx = np.asarray([x[3] for x in layout], np.int32)
    rng = np.random.RandomState(7)
    q = jnp.asarray(rng.randn(4, 4, 32), jnp.float32)
    out = np.asarray(ragged_paged_attention(
        q, kp, vp, jnp.asarray(tbl), seq_slots, q_starts, q_lens, ctx,
        interpret=True))
    ref = np.asarray(ragged_paged_attention_reference(
        q, kp, vp, tbl, seq_slots, q_starts, q_lens, ctx))
    for _, qs, ql, _ in layout:
        np.testing.assert_allclose(out[qs:qs + ql], ref[qs:qs + ql],
                                   rtol=2e-5, atol=2e-5)


def test_kernel_matches_decode_kernel_on_pure_decode():
    """A pure-decode ragged batch runs the SAME streaming recurrence as
    the fixed-shape decode kernel — outputs agree to float tolerance."""
    from paddle_tpu.ops.pallas.paged_attention import paged_attention
    kp, vp, tbl = _pool(nslots=3)
    lens = np.asarray([7, 19, 30], np.int32)
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(3, 4, 32), jnp.float32)
    legacy = np.asarray(paged_attention(q, kp, vp, jnp.asarray(tbl),
                                        jnp.asarray(lens), interpret=True))
    ragged = np.asarray(ragged_paged_attention(
        q, kp, vp, jnp.asarray(tbl), np.arange(3, dtype=np.int32),
        np.arange(3, dtype=np.int32), np.ones(3, np.int32), lens,
        interpret=True))
    np.testing.assert_allclose(ragged, legacy, rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# engine acceptance: ragged scheduler == legacy two-program path == oracle
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    return LlamaForCausalLM(llama_tiny(num_hidden_layers=2,
                                       max_position_embeddings=256))


def _oracle(model, p, n):
    return np.asarray(model.generate(paddle.to_tensor(p),
                                     max_new_tokens=n)._data)


def test_ragged_vs_legacy_mixed_workload_bit_identical(model):
    """The PR's acceptance bar: a mixed 8-request workload (shared
    prefixes, staggered arrivals, one timeout cancellation) produces
    greedy outputs bit-identical between the ragged token-budget
    scheduler and the legacy chunked+decode path — and both match the
    dense oracle."""
    rng = np.random.RandomState(0)
    shared = rng.randint(0, 128, 48)
    specs = [3, 9, 5, 14, 7, 4, 11, 6]           # unique tail lengths
    prompts = [np.concatenate([shared, rng.randint(0, 128, t)])
               .astype(np.int64)[None] for t in specs]

    def run(ragged):
        eng = ContinuousServingEngine(
            model, max_batch_size=4, max_len=96, page_size=16,
            prefill_chunk_tokens=24, token_budget=32, enable_ragged=ragged)
        results = [None] * len(prompts)
        with eng:
            # request 0 lands first and registers the shared prefix
            results[0] = np.asarray(eng.generate(
                prompts[0], max_new_tokens=6, timeout=300).numpy())

            def call(i):
                time.sleep(0.01 * i)             # staggered arrivals
                results[i] = np.asarray(eng.generate(
                    prompts[i], max_new_tokens=6, timeout=300).numpy())

            threads = [threading.Thread(target=call, args=(i,))
                       for i in range(1, len(prompts))]
            for t in threads:
                t.start()
            # one extra request that gives up while the engine is busy
            with pytest.raises(TimeoutError):
                eng.generate(prompts[0], max_new_tokens=30, timeout=0.001)
            for t in threads:
                t.join()
            deadline = time.time() + 60
            while eng.cancelled_rows < 1 and time.time() < deadline:
                time.sleep(0.01)
        assert eng.cancelled_rows >= 1
        return results, eng

    got_r, eng_r = run(True)
    got_l, eng_l = run(False)
    for a, b in zip(got_r, got_l):
        np.testing.assert_array_equal(a, b)
    for i in (0, 4):                             # spot-check dense oracle
        np.testing.assert_array_equal(got_r[i],
                                      _oracle(model, prompts[i], 6))
    # the ragged run really used the single program family, with both
    # prefill and decode tokens flowing through it
    assert eng_r.ragged_steps > 0
    assert eng_r.ragged_prefill_tokens > 0
    assert eng_r.ragged_decode_tokens > 0
    assert eng_l.ragged_steps == 0
    # prefix-cache hits happened under the ragged scheduler too
    assert eng_r._cache.prefix_hits > 0


def test_ragged_bucket_set_bounded(model):
    """Every compiled shape the scheduler runs must come from the
    declared bucket family — no per-request shapes, no unbounded
    recompiles — and the per-tick pack never exceeds the budget."""
    rng = np.random.RandomState(2)
    prompts = [rng.randint(0, 128, (1, n)).astype(np.int64)
               for n in (29, 4, 17, 40)]
    eng = ContinuousServingEngine(model, max_batch_size=4, max_len=64,
                                  token_budget=16, prefill_chunk_tokens=64)
    with eng:
        threads = [threading.Thread(
            target=lambda p=p: eng.generate(p, max_new_tokens=4,
                                            timeout=300))
            for p in prompts]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert eng.ragged_steps > 0
    assert eng.ragged_buckets_used, "no ragged step ran"
    assert eng.ragged_buckets_used <= eng.declared_token_buckets(), (
        eng.ragged_buckets_used, eng.declared_token_buckets())
    assert max(eng.ragged_buckets_used) <= eng.token_budget
    # a 40-token prompt through a 16-token budget takes several ticks
    assert eng.ragged_steps >= 3


def test_ragged_respects_chunk_cap_and_emits_events(model):
    """prefill_chunk_tokens still caps any ONE sequence's per-tick span
    (fairness), and the scheduler emits legacy-compatible chunk/decode
    events so liveness remains observable."""
    rng = np.random.RandomState(3)
    p = rng.randint(0, 128, (1, 40)).astype(np.int64)
    eng = ContinuousServingEngine(model, max_batch_size=2, max_len=64,
                                  prefill_chunk_tokens=8, token_budget=64)
    with eng:
        out = np.asarray(eng.generate(p, max_new_tokens=2,
                                      timeout=300).numpy())
    np.testing.assert_array_equal(out, _oracle(model, p, 2))
    chunks = [e for e in eng.events if e[0] == "chunk"]
    assert len(chunks) >= 5                      # ceil(40/8)
    assert max(c[2] for c in chunks) <= 8
    assert eng.prefill_chunks == len(chunks)


def test_ragged_env_knobs(model, monkeypatch):
    monkeypatch.setenv("PADDLE_SERVING_RAGGED", "0")
    assert ContinuousServingEngine(model).enable_ragged is False
    monkeypatch.setenv("PADDLE_SERVING_RAGGED", "1")
    monkeypatch.setenv("PADDLE_SERVING_TOKEN_BUDGET", "128")
    eng = ContinuousServingEngine(model)
    assert eng.enable_ragged is True
    assert eng.token_budget == 128
    # budget is clamped so every decode slot keeps its per-tick token
    monkeypatch.setenv("PADDLE_SERVING_TOKEN_BUDGET", "4")
    assert ContinuousServingEngine(
        model, max_batch_size=8).token_budget == 8


def test_ragged_telemetry_and_flight_state(model):
    from paddle_tpu.profiler import metrics
    from paddle_tpu.inference.serving import _engine_state
    rng = np.random.RandomState(4)
    p = rng.randint(0, 128, (1, 20)).astype(np.int64)
    eng = ContinuousServingEngine(model, max_batch_size=2, max_len=48,
                                  token_budget=16)
    with eng:
        eng.generate(p, max_new_tokens=3, timeout=300)
        state = _engine_state(eng)
    snap = metrics()
    ragged = snap["paddle_serving_ragged_tokens_total"]["series"]
    assert ragged.get("prefill", 0) >= 20
    assert ragged.get("decode", 0) >= 2
    util = snap["paddle_serving_token_budget_utilization"]["series"][""]
    assert util["count"] >= eng.ragged_steps > 0
    # flight-recorder state provider carries the ragged scheduler fields
    for key in ("ragged_steps", "token_budget", "ragged_prefill_tokens",
                "ragged_decode_tokens", "ragged_buckets_used",
                "padded_tokens_total", "useful_tokens_total"):
        assert key in state, key
    assert state["ragged"] is True
