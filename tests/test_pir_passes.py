"""Program-level pass/rewrite infra (VERDICT.md round-3 missing item 5;
reference: PIR pattern rewriter + inference analysis passes — SURVEY.md
§2.1 "PIR"). The lowered program is StableHLO; the infra must inspect it,
rewrite it (MLIR pipelines and Python pattern rewrites), and round-trip
back to an EXECUTABLE program."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import export as jexport

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.static.pir import (MLIRPipelinePass, PatternRewritePass,
                                   ProgramIR, optimize_exported, registry)


def _export(fn, *example):
    return jexport.export(jax.jit(fn))(*example)


def test_inspect_op_histogram_and_walk():
    def f(x):
        return jnp.sin(x) * jnp.cos(x) + x

    pir = ProgramIR.from_exported(_export(f, jnp.zeros((4,))))
    hist = pir.op_histogram()
    assert hist.get("stablehlo.sine") == 1
    assert hist.get("stablehlo.cosine") == 1
    assert len(pir.ops("stablehlo.multiply")) == 1
    assert "stablehlo.sine" in pir.text


def test_cse_pass_merges_duplicate_ops_and_executes():
    def f(x):
        return jnp.sin(x) + jnp.sin(x)     # two identical subtrees

    exp = _export(f, jnp.zeros((4,)))
    pir = ProgramIR.from_exported(exp)
    assert pir.op_histogram().get("stablehlo.sine") == 2
    changed = pir.apply(["ir_optim"])
    assert changed
    assert pir.op_histogram().get("stablehlo.sine") == 1
    x = jnp.asarray([1.0, 2.0, 3.0, 4.0])
    out = pir.to_exported().call(x)
    np.testing.assert_allclose(np.asarray(out), 2 * np.sin(x), rtol=1e-6)


def test_pattern_rewrite_pass_python_level():
    """The drr-analogue: match by name+predicate, mutate via the MLIR
    python API — here: retarget multiply to divide (program surgery XLA
    would never do on its own)."""
    def f(x, y):
        return jnp.sin(x) * y

    exp = _export(f, jnp.zeros((4,)), jnp.zeros((4,)))
    pir = ProgramIR.from_exported(exp)

    from jaxlib.mlir import ir

    def to_divide(op):
        with pir._ctx, ir.Location.unknown():
            ir.InsertionPoint(op).insert(  # build divide next to multiply
                new := ir.Operation.create(
                    "stablehlo.divide", [r.type for r in op.results],
                    list(op.operands)))
            for old_r, new_r in zip(op.results, new.results):
                old_r.replace_all_uses_with(new_r)
            op.erase()

    changed = pir.apply([PatternRewritePass(
        "mul-to-div", lambda op: op.name == "stablehlo.multiply",
        to_divide)])
    assert changed
    x = jnp.asarray([1.0, 2.0, 3.0, 4.0])
    y = jnp.asarray([2.0, 2.0, 2.0, 2.0])
    out = pir.to_exported().call(x, y)
    np.testing.assert_allclose(np.asarray(out), np.sin(x) / 2, rtol=1e-6)


def test_registry_and_unknown_pass():
    assert {"canonicalize", "cse", "ir_optim"} <= set(registry.names())
    with pytest.raises(KeyError, match="unknown pass"):
        registry.get("nope")


def test_predictor_ir_optim_knob(tmp_path):
    """Config.switch_ir_optim(True) runs the pipeline on the loaded
    program and the Predictor still serves correct outputs."""
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.jit import InputSpec

    paddle.seed(2)
    net = nn.Sequential(nn.Linear(8, 8), nn.Tanh(), nn.Linear(8, 2))
    net.eval()
    xs = np.random.RandomState(0).randn(4, 8).astype(np.float32)
    want = net(paddle.to_tensor(xs)).numpy()
    prefix = str(tmp_path / "m")
    paddle.jit.save(net, prefix, input_spec=[InputSpec([4, 8], "float32")])

    cfg = Config(prefix)
    cfg.switch_ir_optim(True)
    pred = create_predictor(cfg)
    (got,) = pred.run([xs])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
