"""Regressions for code-review findings (round 1)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.nn import functional as F


def test_split_non_divisible_raises():
    x = paddle.ones([5, 2])
    with pytest.raises(ValueError):
        paddle.split(x, 2, axis=0)


def test_dropout_downscale_in_infer():
    x = paddle.ones([100])
    out = F.dropout(x, p=0.4, training=False, mode="downscale_in_infer")
    np.testing.assert_allclose(out.numpy(), 0.6, rtol=1e-6)
    out_train = F.dropout(x, p=0.4, training=True, mode="downscale_in_infer")
    vals = set(np.round(np.unique(out_train.numpy()), 4).tolist())
    assert vals <= {0.0, 1.0}  # no upscaling in train for this mode


def test_maxpool_ceil_mode():
    x = paddle.to_tensor(np.arange(25, dtype=np.float32).reshape(1, 1, 5, 5))
    out_floor = F.max_pool2d(x, 2, stride=2, ceil_mode=False)
    assert out_floor.shape == [1, 1, 2, 2]
    out_ceil = F.max_pool2d(x, 2, stride=2, ceil_mode=True)
    assert out_ceil.shape == [1, 1, 3, 3]
    np.testing.assert_allclose(out_ceil.numpy()[0, 0, 2], [21, 23, 24])


def test_avgpool_ceil_mode_counts_real_elements():
    x = paddle.ones([1, 1, 5, 5])
    out = F.avg_pool2d(x, 2, stride=2, ceil_mode=True)
    assert out.shape == [1, 1, 3, 3]
    # partial windows average only real elements -> still 1.0
    np.testing.assert_allclose(out.numpy(), 1.0, rtol=1e-6)


def test_group_norm_nhwc():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 3, 4).astype(np.float32)  # NHWC, C=4
    out = F.group_norm(paddle.to_tensor(x), 2, data_format="NHWC")
    ref = F.group_norm(paddle.to_tensor(np.transpose(x, (0, 3, 1, 2))), 2,
                       data_format="NCHW")
    np.testing.assert_allclose(out.numpy(),
                               np.transpose(ref.numpy(), (0, 2, 3, 1)),
                               rtol=1e-5, atol=1e-5)


def test_lamb_exclude_from_weight_decay():
    p1 = paddle.Parameter(np.ones(3, np.float32))
    p2 = paddle.Parameter(np.ones(3, np.float32))
    p2.name = "norm_weight"
    opt = optimizer.Lamb(learning_rate=0.1, lamb_weight_decay=0.5,
                         parameters=[p1, p2],
                         exclude_from_weight_decay_fn=lambda p: "norm" in p.name)
    p1.grad = paddle.zeros([3])
    p2.grad = paddle.zeros([3])
    opt.step()
    # p1 decays (update = wd*p scaled by trust ratio), p2 does not move
    assert not np.allclose(p1.numpy(), 1.0)
    np.testing.assert_allclose(p2.numpy(), 1.0)


def test_cummax_returns_indices():
    x = paddle.to_tensor([3.0, 1.0, 4.0, 4.0, 2.0])
    vals, idx = paddle.cummax(x, axis=0)
    np.testing.assert_allclose(vals.numpy(), [3, 3, 4, 4, 4])
    np.testing.assert_array_equal(idx.numpy(), [0, 0, 2, 2, 2])  # earliest tie
    vals2, idx2 = paddle.cummin(x, axis=0)
    np.testing.assert_allclose(vals2.numpy(), [3, 1, 1, 1, 1])
    np.testing.assert_array_equal(idx2.numpy(), [0, 1, 1, 1, 1])


def test_cross_entropy_soft_label_with_weight():
    logits = paddle.to_tensor(np.zeros((2, 3), np.float32))
    soft = paddle.to_tensor(np.array([[1, 0, 0], [0, 0, 1]], np.float32))
    w = paddle.to_tensor(np.array([2.0, 1.0, 0.0], np.float32))
    loss = F.cross_entropy(logits, soft, weight=w, soft_label=True,
                           reduction="none")
    # uniform logits -> lp = log(1/3); weighted: row0: -2*lp, row1: -0*lp
    lp = np.log(1 / 3)
    np.testing.assert_allclose(loss.numpy(), [-2 * lp, 0.0], rtol=1e-5)


def test_interpolate_align_corners():
    x = paddle.to_tensor(np.array([[[[0.0, 1.0], [2.0, 3.0]]]], np.float32))
    out = F.interpolate(x, size=(3, 3), mode="bilinear", align_corners=True)
    # corners preserved exactly; center = mean
    np.testing.assert_allclose(out.numpy()[0, 0, 0, 0], 0.0, atol=1e-6)
    np.testing.assert_allclose(out.numpy()[0, 0, 2, 2], 3.0, atol=1e-6)
    np.testing.assert_allclose(out.numpy()[0, 0, 1, 1], 1.5, atol=1e-6)
    # 2->4: align_corners grid {0,1/3,2/3,1} differs from half-pixel grid
    out_ac = F.interpolate(x, size=(4, 4), mode="bilinear", align_corners=True)
    out_hp = F.interpolate(x, size=(4, 4), mode="bilinear", align_corners=False)
    np.testing.assert_allclose(out_ac.numpy()[0, 0, 3, 3], 3.0, atol=1e-6)
    assert not np.allclose(out_ac.numpy(), out_hp.numpy())
