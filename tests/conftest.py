"""Test config: force a CPU backend with 8 virtual devices BEFORE any backend
initialization, so distributed tests can build a [dp, pp, sharding, sep, mp]
mesh without TPU hardware (SURVEY.md §4 takeaway 4).

Note: this environment's sitecustomize registers an 'axon' TPU plugin and
programmatically sets jax_platforms='axon,cpu'; a plain JAX_PLATFORMS env var
is NOT enough — we must override via jax.config before the first dispatch,
otherwise every test process tries to claim the single TPU tunnel."""
import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags +
                               " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# full-precision matmuls for numeric parity checks (the perf path uses the
# backend default — bf16 passes on TPU MXU)
jax.config.update("jax_default_matmul_precision", "highest")

import functools  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_all():
    import paddle_tpu as paddle
    paddle.seed(102)
    np.random.seed(102)
    yield


# ---------------------------------------------------------------------------
# SPMD pipeline-engine guard: recent jax CPU builds reject the PartitionId
# instruction under SPMD partitioning ("UNIMPLEMENTED: PartitionId
# instruction is not supported for SPMD partitioning..."), which the
# shard_map-based pipeline engine needs. That is a backend limitation, not
# a pipeline bug — probe it ONCE and skip (with the backend's own reason)
# the tests that require it, so tier-1 signal stays clean without touching
# pipeline code paths. On backends where the probe passes (real TPU, older
# jax CPU), the tests run unchanged.
# ---------------------------------------------------------------------------

_SPMD_PIPELINE_PROBE = {"done": False, "ok": True, "reason": ""}


def spmd_pipeline_supported():
    """True when a minimal jitted `pipeline_forward` program compiles on
    this backend. Cached for the process; any failure OTHER than the
    known unsupported-instruction condition counts as supported so real
    regressions still surface in the tests themselves."""
    p = _SPMD_PIPELINE_PROBE
    if not p["done"]:
        p["done"] = True
        import jax
        import jax.numpy as jnp
        from paddle_tpu.distributed import mesh as mesh_mod
        from paddle_tpu.distributed.engine import pipeline_forward

        def _stage(params, x):
            return x * params

        try:
            mesh_mod.init_mesh({"dp": 2, "pp": 4})
            ws = jnp.ones((4, 1), jnp.float32)
            micro = jnp.ones((4, 1, 1), jnp.float32)
            jax.jit(lambda w, x: pipeline_forward(_stage, w, x))(
                ws, micro)
        except Exception as e:  # noqa: BLE001 — classified below
            msg = str(e)
            if "PartitionId" in msg or ("SPMD" in msg
                                        and "UNIMPLEMENTED" in msg):
                p["ok"] = False
                p["reason"] = msg.splitlines()[0][:200]
        finally:
            mesh_mod.reset_mesh()
    return p["ok"]


def requires_spmd_pipeline(fn):
    """Decorator for tests that run the SPMD pipeline engine: skip at
    run time (probe evaluated lazily, once) when the backend cannot
    partition it."""
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if not spmd_pipeline_supported():
            pytest.skip("SPMD pipeline engine unsupported on this "
                        f"backend: {_SPMD_PIPELINE_PROBE['reason']}")
        return fn(*args, **kwargs)
    return wrapper


# ---------------------------------------------------------------------------
# fast/slow tiers (VERDICT round-3 item 9): the full suite is ~50 min on the
# 8-virtual-device CPU mesh, so per-commit signal needs a fast tier —
# `pytest tests/ -m "not slow"` runs in ~15 min on the 1-core CPU box
# (PR-18 measurement). Files measured >15 s in the round-4 full run are
# marked slow here (file-level: coarse but maintainable; re-measure with
# `pytest --durations=0` when adding suites).
# ---------------------------------------------------------------------------

_SLOW_FILES = {
    "test_bert_to_static.py", "test_config4_16dev.py",
    "test_config5_32dev.py", "test_detection_ops.py",
    "test_continuous_batching.py", "test_distributed.py",
    "test_distribution.py", "test_fft_signal_vision_ops.py",
    "test_functional_ops.py", "test_fused_multi_transformer.py",
    "test_generation.py", "test_guarded_compile.py", "test_hf_pretrained.py",
    "test_hybrid_3d.py", "test_io_vision.py", "test_launch_multiproc.py",
    "test_llama_context_parallel.py", "test_mixtral.py",
    "test_models.py", "test_moe.py",
    "test_nn.py", "test_nn_extras.py", "test_op_suite.py",
    "test_op_surface_r3.py", "test_paged_attention.py",
    "test_pallas_flash.py", "test_pipeline_1f1b.py",
    "test_pipeline_dropout.py", "test_pipeline_transformer.py",
    "test_quant_inference.py", "test_review_fixes.py", "test_rnn.py",
    "test_serving.py", "test_sharding_offload.py", "test_sparse_quant.py",
    "test_tcp_store.py", "test_training_e2e.py", "test_ulysses.py",
    "test_vision_zoo2.py", "test_zero_memory.py",
}


def pytest_collection_modifyitems(config, items):
    import os.path
    for item in items:
        if os.path.basename(str(item.fspath)) in _SLOW_FILES:
            item.add_marker(pytest.mark.slow)
