"""Test config: force a CPU backend with 8 virtual devices BEFORE any backend
initialization, so distributed tests can build a [dp, pp, sharding, sep, mp]
mesh without TPU hardware (SURVEY.md §4 takeaway 4).

Note: this environment's sitecustomize registers an 'axon' TPU plugin and
programmatically sets jax_platforms='axon,cpu'; a plain JAX_PLATFORMS env var
is NOT enough — we must override via jax.config before the first dispatch,
otherwise every test process tries to claim the single TPU tunnel."""
import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags +
                               " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# full-precision matmuls for numeric parity checks (the perf path uses the
# backend default — bf16 passes on TPU MXU)
jax.config.update("jax_default_matmul_precision", "highest")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_all():
    import paddle_tpu as paddle
    paddle.seed(102)
    np.random.seed(102)
    yield
