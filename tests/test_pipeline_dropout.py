"""Dropout through the jitted SPMD pipeline engine (VERDICT.md round-2
item 5): the engine threads deterministic per-(microbatch, chunk) PRNG
keys through the scan — reference semantics: ``RNGStatesTracker``
(``fleet/layers/mpu/random.py``) gives every microbatch an independent,
schedule-invariant dropout stream, so a pipelined run with dropout must
reproduce a sequential run with the same base key."""
import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.engine import (PipelinedModule, _chunk_key,
                                           pipeline_forward)


# ---------------------------------------------------------------------------
# engine level: stochastic stage_fn
# ---------------------------------------------------------------------------

def _stoch_stage(params, x, key):
    w, b = params
    keep = jax.random.bernoulli(key, 0.8, x.shape)
    return jnp.tanh(x @ w + b) * keep


def _setup(n_chunks=4, n_micro=6, mb=2, d=8, seed=0):
    rng = np.random.default_rng(seed)
    ws = jnp.asarray(rng.normal(size=(n_chunks, d, d)) * 0.5, jnp.float32)
    bs = jnp.asarray(rng.normal(size=(n_chunks, d)) * 0.1, jnp.float32)
    micro = jnp.asarray(rng.normal(size=(n_micro, mb, d)), jnp.float32)
    return (ws, bs), micro


def _sequential_with_keys(params, micro, base_key):
    ws, bs = params
    out = []
    for m in range(micro.shape[0]):
        x = micro[m]
        for c in range(ws.shape[0]):
            x = _stoch_stage((ws[c], bs[c]), x, _chunk_key(base_key, m, c))
        out.append(x)
    return jnp.stack(out)


def test_stochastic_pipeline_matches_sequential():
    mesh_mod.init_mesh({"dp": 2, "pp": 4})
    try:
        params, micro = _setup()
        base = jax.random.key(42)
        out = jax.jit(lambda p, x, k: pipeline_forward(
            _stoch_stage, p, x, rng_key=k))(params, micro, base)
        ref = _sequential_with_keys(params, micro, base)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
        # a different base key gives different masks (dropout is live)
        out2 = jax.jit(lambda p, x, k: pipeline_forward(
            _stoch_stage, p, x, rng_key=k))(params, micro,
                                            jax.random.key(43))
        assert float(jnp.max(jnp.abs(out2 - out))) > 1e-3
    finally:
        mesh_mod.reset_mesh()


def test_stochastic_pipeline_vpp_matches_sequential():
    mesh_mod.init_mesh({"pp": 2, "mp": 4})
    try:
        params, micro = _setup(n_chunks=4)
        base = jax.random.key(7)
        out = pipeline_forward(_stoch_stage, params, micro, vpp_degree=2,
                               rng_key=base)
        ref = _sequential_with_keys(params, micro, base)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
    finally:
        mesh_mod.reset_mesh()


def test_stochastic_grad_matches_sequential():
    mesh_mod.init_mesh({"pp": 4, "dp": 2})
    try:
        params, micro = _setup(n_micro=4)
        base = jax.random.key(3)
        g = jnp.asarray(np.random.default_rng(9).normal(size=(4, 2, 8)),
                        jnp.float32)

        def loss_pipe(p):
            return jnp.sum(pipeline_forward(_stoch_stage, p, micro,
                                            rng_key=base) * g)

        def loss_seq(p):
            return jnp.sum(_sequential_with_keys(p, micro, base) * g)

        gp = jax.jit(jax.grad(loss_pipe))(params)
        gs = jax.grad(loss_seq)(params)
        for a, b in zip(jax.tree.leaves(gp), jax.tree.leaves(gs)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)
    finally:
        mesh_mod.reset_mesh()


# ---------------------------------------------------------------------------
# PipelinedModule level: real nn.Dropout blocks
# ---------------------------------------------------------------------------

class _DropBlock(nn.Layer):
    def __init__(self, d, p):
        super().__init__()
        self.fc = nn.Linear(d, d)
        self.drop = nn.Dropout(p)

    def forward(self, x):
        return x + self.drop(paddle.tanh(self.fc(x)))


def _make_drop_pipe(d=8, p=0.5, n_blocks=4, num_stages=2):
    from paddle_tpu.distributed.fleet import PipelineLayer, LayerDesc
    paddle.seed(11)
    pl = PipelineLayer(
        layers=[LayerDesc(_DropBlock, d, p) for _ in range(n_blocks)],
        num_stages=num_stages, loss_fn=nn.MSELoss())
    pl.train()
    return pl


def test_pipelined_module_dropout_matches_manual_derivation():
    mesh_mod.init_mesh({"dp": 4, "pp": 2})
    try:
        pl = _make_drop_pipe()
        pm = PipelinedModule(pl)
        rng = np.random.default_rng(0)
        micro = jnp.asarray(rng.normal(size=(4, 2, 8)), jnp.float32)
        base = jax.random.key(5)
        out = pm(pm.edge_arrays(), pm.stacked_arrays(), micro, rng_key=base)

        # manual oracle: same key derivation, sequential schedule
        stacked = pm.stacked_arrays()
        flat = [a.reshape((-1,) + tuple(a.shape[2:])) for a in stacked]
        ref = []
        for m in range(micro.shape[0]):
            x = micro[m]
            for c in range(pm.n_chunks):
                ck = _chunk_key(base, m, c)
                for l in range(pm.lpc):
                    arrs = [a[c * pm.lpc + l] for a in flat]
                    x, _ = pm._fm_blk(arrs, [], jax.random.fold_in(ck, l), x)
            ref.append(x)
        ref = jnp.stack(ref)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
        # dropout is really live: constant-key path differs
        out_const = pm(pm.edge_arrays(), pm.stacked_arrays(), micro)
        assert float(jnp.max(jnp.abs(out - out_const))) > 1e-4
    finally:
        mesh_mod.reset_mesh()


def test_gpt_pipe_trains_with_dropout():
    """GPTForCausalLMPipe (stochastic blocks: attention + residual
    dropout) trains through the SPMD engine with key threading — the
    config-4 model family (GPT dp x pp, BASELINE.json configs[3])."""
    from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel import (
        PipelineParallel)
    from paddle_tpu.framework.core import Tensor
    from paddle_tpu.models import GPTForCausalLMPipe, gpt_tiny

    mesh_mod.init_mesh({"dp": 4, "pp": 2})
    try:
        paddle.seed(9)
        cfg = gpt_tiny(num_hidden_layers=2)
        pipe = GPTForCausalLMPipe(cfg, num_stages=2)
        pipe.train()
        pp = PipelineParallel(pipe)
        pp.accumulate_steps = 2
        opt = paddle.optimizer.AdamW(learning_rate=3e-3,
                                     parameters=pipe.parameters())
        rng = np.random.default_rng(3)
        ids = Tensor(jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 16)),
                                 jnp.int32))
        labels = Tensor(jnp.asarray(
            rng.integers(0, cfg.vocab_size, (4, 16)), jnp.int32))
        losses = [float(pp.train_batch([ids, labels], opt))
                  for _ in range(10)]
        assert pp._spmd and pp._needs_key
        assert losses[-1] < losses[0], losses
        # tied embedding: the two SharedLayerDesc instances hold ONE
        # Parameter, and it appears exactly once in the edge params
        # (position table shares the shape, hence identity-based check)
        from paddle_tpu.models.gpt import GPTWordEmbeddingPipe
        shared = [l.word_embeddings.weight for l in pipe.run_function
                  if isinstance(l, GPTWordEmbeddingPipe)]
        assert len(shared) == 2 and shared[0] is shared[1]
        pm = pp._spmd
        assert sum(1 for p in pm.edge_params if p is shared[0]) == 1
    finally:
        mesh_mod.reset_mesh()


def test_train_batch_spmd_with_dropout_no_fallback():
    """PipelineParallel.train_batch keeps the SPMD engine (no eager
    fallback) for a dropout model, and training reduces the loss."""
    from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel import (
        PipelineParallel)
    from paddle_tpu.framework.core import Tensor

    mesh_mod.init_mesh({"dp": 4, "pp": 2})
    try:
        pl = _make_drop_pipe(p=0.2)
        pp = PipelineParallel(pl)
        pp.accumulate_steps = 2
        opt = paddle.optimizer.AdamW(learning_rate=5e-3,
                                     parameters=pl.parameters())
        rng = np.random.default_rng(1)
        x = Tensor(jnp.asarray(rng.normal(size=(8, 8)), jnp.float32))
        y = Tensor(jnp.zeros((8, 8), jnp.float32))
        losses = [float(pp.train_batch([x, y], opt)) for _ in range(30)]
        assert pp._spmd, "dropout model must use the SPMD engine now"
        assert pp._needs_key is True
        assert np.mean(losses[-5:]) < np.mean(losses[:5]) - 0.05, losses
    finally:
        mesh_mod.reset_mesh()
