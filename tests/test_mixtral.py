"""Mixtral sparse-MoE LM family (reference behavior: PaddleNLP
``mixtral/modeling.py`` — top-k routed SwiGLU experts + router
load-balance aux loss on a Llama-style trunk). The sparse block reuses
the shared GShard dispatch plan; parity is checked against a per-token
dense-routing oracle at over-provisioned capacity (no drops)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import (MixtralConfig, MixtralForCausalLM,
                               MixtralSparseMoeBlock, mixtral_tiny)


def test_moe_block_matches_dense_routing_oracle():
    """At capacity >= S·k/E every routed token is kept, so the einsum
    dispatch must equal the naive per-token top-k mixture."""
    paddle.seed(0)
    cfg = mixtral_tiny(moe_capacity_factor=8.0)    # over-provisioned
    blk = MixtralSparseMoeBlock(cfg)
    blk.eval()
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.normal(size=(2, 6, cfg.hidden_size))
                         .astype("float32"))
    out, _aux = blk(x)
    out = out.numpy()

    gw = blk.gate.weight.numpy()
    wg, wu, wd = (blk.w_gate.numpy(), blk.w_up.numpy(), blk.w_down.numpy())
    tok = x.numpy().reshape(-1, cfg.hidden_size)
    probs = np.asarray(jax.nn.softmax(tok @ gw, axis=-1))
    want = np.zeros_like(tok)
    for i, t in enumerate(tok):
        top = np.argsort(-probs[i])[:cfg.num_experts_per_tok]
        w = probs[i, top] / probs[i, top].sum()
        for ww, e in zip(w, top):
            h = (np.asarray(jax.nn.silu(t @ wg[e]))) * (t @ wu[e])
            want[i] += ww * (h @ wd[e])
    np.testing.assert_allclose(out.reshape(-1, cfg.hidden_size), want,
                               rtol=2e-4, atol=2e-5)


def test_mixtral_train_step_decreases_loss_with_aux():
    paddle.seed(1)
    cfg = mixtral_tiny()
    model = MixtralForCausalLM(cfg)
    model.train()
    opt = paddle.optimizer.AdamW(learning_rate=3e-3,
                                 parameters=model.parameters())
    rng = np.random.default_rng(1)
    ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (2, 16))
                           .astype("int32"))
    labels = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (2, 16))
                              .astype("int32"))
    losses = []
    for _ in range(8):
        loss, _ = model(ids, labels=labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses
    # the aux loss is real and participates: every layer produced one
    auxes = model.mixtral.aux_losses()
    assert len(auxes) == cfg.num_hidden_layers
    assert all(float(a.numpy() if hasattr(a, "numpy") else a) >= 0
               for a in auxes)


def test_mixtral_recompute_trains_with_aux_grads():
    """use_recompute: the aux loss must cross the jax.checkpoint
    boundary as a RETURN value (a side-channel attribute would leak an
    inner-trace tracer) and the router must still receive gradient."""
    from paddle_tpu.framework.functional import FunctionalModule

    paddle.seed(4)
    cfg = mixtral_tiny(use_recompute=True)
    model = MixtralForCausalLM(cfg)
    model.train()
    fm = FunctionalModule(model, training=True)
    rng = np.random.default_rng(4)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 16)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 16)),
                         jnp.int32)
    key = fm.next_key()

    def loss_fn(ps):
        (loss, _), _ = fm(ps, [], key, ids, labels=labels)
        return loss

    loss, grads = jax.jit(jax.value_and_grad(loss_fn))(fm.param_arrays())
    assert np.isfinite(float(loss))
    # router (gate) weights get non-zero gradient through the aux loss
    gate_idx = [i for i, (n, p) in enumerate(
        (n, p) for n, p in model.named_parameters() if p is not None)
        if "gate.weight" in n]
    assert gate_idx, "no router gate params found"
    assert any(float(jnp.abs(grads[i]).sum()) > 0 for i in gate_idx), \
        "router received zero gradient under recompute"


def test_mixtral_ep_nondivisible_replicates():
    """4 experts on a dp=8 mesh must replicate (not crash) — param_specs
    drops non-divisible rule axes and the block skips the EP constraint."""
    from jax.sharding import NamedSharding
    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.framework.functional import FunctionalModule

    paddle.seed(5)
    cfg = mixtral_tiny(num_local_experts=4)     # 4 % 8 != 0
    model = MixtralForCausalLM(cfg)
    model.train()
    fm = FunctionalModule(model, training=True)
    mesh = mesh_mod.init_mesh({"dp": 8})
    try:
        specs = fm.param_specs(MixtralForCausalLM.sharding_rules())
        p_arrs = [jax.device_put(a, NamedSharding(mesh, s))
                  for a, s in zip(fm.param_arrays(), specs)]   # no raise
        rng = np.random.default_rng(5)
        ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (8, 8)), jnp.int32)
        labels = jnp.asarray(rng.integers(0, cfg.vocab_size, (8, 8)),
                             jnp.int32)
        key = fm.next_key()

        def loss_fn(ps):
            (loss, _), _ = fm(ps, [], key, ids, labels=labels)
            return loss

        with mesh:
            loss = jax.jit(loss_fn)(p_arrs)
        assert np.isfinite(float(loss))
    finally:
        mesh_mod.reset_mesh()


def test_mixtral_generate_smoke():
    paddle.seed(2)
    cfg = mixtral_tiny()
    model = MixtralForCausalLM(cfg)
    model.eval()
    ids = paddle.to_tensor(
        np.random.default_rng(2).integers(0, cfg.vocab_size, (1, 4))
        .astype("int32"))
    out = model.generate(ids, max_new_tokens=6)
    out = out[0] if isinstance(out, tuple) else out
    assert out.shape[-1] >= 10


def test_mixtral_ep_sharded_step():
    """Expert-parallel training step: expert dim of the stacked weights
    sharded over 'dp' on the 8-device mesh, one jitted fwd+bwd."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.framework.functional import FunctionalModule

    paddle.seed(3)
    cfg = mixtral_tiny(num_local_experts=8)
    model = MixtralForCausalLM(cfg)
    model.train()
    fm = FunctionalModule(model, training=True)
    mesh = mesh_mod.init_mesh({"dp": 8})
    try:
        specs = fm.param_specs(MixtralForCausalLM.sharding_rules())
        shards = [NamedSharding(mesh, s) for s in specs]
        p_arrs = [jax.device_put(a, sh)
                  for a, sh in zip(fm.param_arrays(), shards)]
        rng = np.random.default_rng(3)
        ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (8, 8)), jnp.int32)
        labels = jnp.asarray(rng.integers(0, cfg.vocab_size, (8, 8)),
                             jnp.int32)
        key = fm.next_key()

        def loss_fn(ps):
            (loss, _), _ = fm(ps, [], key, ids, labels=labels)
            return loss

        step = jax.jit(jax.value_and_grad(loss_fn), in_shardings=(shards,))
        with mesh:
            loss, grads = step(p_arrs)
        assert np.isfinite(float(loss))
        assert all(np.isfinite(jax.device_get(g)).all() for g in grads)
        # the expert dim actually sharded over dp
        we = next(a for a, s in zip(p_arrs, specs)
                  if a.ndim == 3 and s == P("dp", None, None))
        assert any(sh.data.shape[0] < we.shape[0]
                   for sh in we.addressable_shards), \
            "expert weights were not ep-sharded"
    finally:
        mesh_mod.reset_mesh()
