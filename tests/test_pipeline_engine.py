"""SPMD pipeline engine tests: forward/grad parity vs sequential stages
(the reference's hybrid_parallel_pp_* parity contract; SURVEY.md §4)."""
import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.engine import pipeline_forward
from conftest import requires_spmd_pipeline


def _stage_fn(params, x):
    w, b = params
    return jnp.tanh(x @ w + b)


def _setup(n_stages=4, n_micro=8, mb=2, d=8, seed=0):
    rng = np.random.default_rng(seed)
    ws = jnp.asarray(rng.normal(size=(n_stages, d, d)) * 0.5, jnp.float32)
    bs = jnp.asarray(rng.normal(size=(n_stages, d)) * 0.1, jnp.float32)
    micro = jnp.asarray(rng.normal(size=(n_micro, mb, d)), jnp.float32)
    return (ws, bs), micro


def _sequential(params, micro):
    ws, bs = params
    out = []
    for m in range(micro.shape[0]):
        x = micro[m]
        for s in range(ws.shape[0]):
            x = _stage_fn((ws[s], bs[s]), x)
        out.append(x)
    return jnp.stack(out)


@requires_spmd_pipeline
def test_pipeline_forward_matches_sequential():
    mesh = mesh_mod.init_mesh({"dp": 2, "pp": 4})
    try:
        params, micro = _setup()
        out = jax.jit(lambda p, x: pipeline_forward(_stage_fn, p, x))(
            params, micro)
        ref = _sequential(params, micro)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
    finally:
        mesh_mod.reset_mesh()


@requires_spmd_pipeline
def test_pipeline_grad_matches_sequential():
    mesh = mesh_mod.init_mesh({"pp": 4, "mp": 2})
    try:
        params, micro = _setup(n_micro=6)
        g = jnp.asarray(np.random.default_rng(9).normal(
            size=(6, 2, 8)), jnp.float32)

        def loss_pipe(p):
            return jnp.sum(pipeline_forward(_stage_fn, p, micro) * g)

        def loss_seq(p):
            return jnp.sum(_sequential(p, micro) * g)

        gp = jax.jit(jax.grad(loss_pipe))(params)
        gs = jax.grad(loss_seq)(params)
        for a, b in zip(jax.tree.leaves(gp), jax.tree.leaves(gs)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)
    finally:
        mesh_mod.reset_mesh()


def test_pipeline_single_stage_fallback():
    mesh = mesh_mod.init_mesh({"dp": 8})
    try:
        params, micro = _setup(n_stages=1, n_micro=4)
        out = pipeline_forward(_stage_fn, params, micro, n_stages=1)
        ref = _sequential(params, micro)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-6, atol=1e-6)
    finally:
        mesh_mod.reset_mesh()


@requires_spmd_pipeline
def test_pipeline_interleaved_vpp_matches_sequential():
    """VPP: 8 chunks over 4 devices (v=2) == sequential 8-layer net."""
    mesh = mesh_mod.init_mesh({"dp": 2, "pp": 4})
    try:
        params, micro = _setup(n_stages=8, n_micro=6)
        out = jax.jit(lambda p, x: pipeline_forward(
            _stage_fn, p, x, vpp_degree=2))(params, micro)
        ref = _sequential(params, micro)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

        g = jnp.asarray(np.random.default_rng(3).normal(
            size=ref.shape), jnp.float32)
        gp = jax.jit(jax.grad(lambda p: jnp.sum(
            pipeline_forward(_stage_fn, p, micro, vpp_degree=2) * g)))(params)
        gs = jax.grad(lambda p: jnp.sum(_sequential(p, micro) * g))(params)
        for a, b in zip(jax.tree.leaves(gp), jax.tree.leaves(gs)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)
    finally:
        mesh_mod.reset_mesh()


@requires_spmd_pipeline
def test_pipeline_trains_with_dp_and_pp():
    """Composition: pp pipeline inside a jitted train step with dp-sharded
    microbatches staying replicated across pp — loss decreases."""
    mesh = mesh_mod.init_mesh({"dp": 2, "pp": 4})
    try:
        params, micro = _setup(n_micro=4)
        target = jnp.zeros((4, 2, 8), jnp.float32)

        @jax.jit
        def step(p):
            def loss_fn(p):
                out = pipeline_forward(_stage_fn, p, micro)
                return jnp.mean((out - target) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(p)
            return loss, jax.tree.map(lambda a, ga: a - 0.1 * ga, p, grads)

        losses = []
        for _ in range(5):
            loss, params = step(params)
            losses.append(float(loss))
        assert losses[-1] < losses[0]
    finally:
        mesh_mod.reset_mesh()
