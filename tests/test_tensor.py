"""Tensor semantics: creation, dtype, mutation, indexing, repr.
Mirrors the reference's tensor API tests (SURVEY.md §4 op unit tests)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_to_tensor_and_numpy():
    x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert x.shape == [2, 2]
    assert str(np.dtype(x.dtype)) == "float32"
    np.testing.assert_allclose(x.numpy(), [[1, 2], [3, 4]])


def test_default_stop_gradient():
    x = paddle.to_tensor([1.0])
    assert x.stop_gradient is True
    p = paddle.Parameter(np.zeros([3]))
    assert p.stop_gradient is False


def test_dtype_conversion():
    x = paddle.to_tensor([1, 2, 3])
    assert str(np.dtype(x.dtype)) == "int64" or str(np.dtype(x.dtype)) == "int32"
    y = x.astype("float32")
    assert str(np.dtype(y.dtype)) == "float32"
    z = x.cast("float16")
    assert str(np.dtype(z.dtype)) == "float16"


def test_arithmetic_dunders():
    a = paddle.to_tensor([1.0, 2.0])
    b = paddle.to_tensor([3.0, 4.0])
    np.testing.assert_allclose((a + b).numpy(), [4, 6])
    np.testing.assert_allclose((a - b).numpy(), [-2, -2])
    np.testing.assert_allclose((a * b).numpy(), [3, 8])
    np.testing.assert_allclose((b / a).numpy(), [3, 2])
    np.testing.assert_allclose((a ** 2).numpy(), [1, 4])
    np.testing.assert_allclose((2.0 - a).numpy(), [1, 0])
    np.testing.assert_allclose((2.0 / a).numpy(), [2, 1])
    np.testing.assert_allclose((-a).numpy(), [-1, -2])


def test_comparison_returns_tensor():
    a = paddle.to_tensor([1.0, 5.0])
    b = paddle.to_tensor([2.0, 2.0])
    lt = a < b
    assert isinstance(lt, paddle.Tensor)
    np.testing.assert_array_equal(lt.numpy(), [True, False])


def test_getitem_setitem():
    x = paddle.arange(12, dtype="float32").reshape([3, 4])
    np.testing.assert_allclose(x[1].numpy(), [4, 5, 6, 7])
    np.testing.assert_allclose(x[:, 1].numpy(), [1, 5, 9])
    np.testing.assert_allclose(x[1:, 2:].numpy(), [[6, 7], [10, 11]])
    x[0, 0] = 100.0
    assert float(x[0, 0]) == 100.0
    idx = paddle.to_tensor([0, 2])
    np.testing.assert_allclose(x[idx].numpy()[1], [8, 9, 10, 11])


def test_inplace_ops():
    x = paddle.ones([3])
    x.add_(paddle.ones([3]))
    np.testing.assert_allclose(x.numpy(), [2, 2, 2])
    x.scale_(0.5)
    np.testing.assert_allclose(x.numpy(), [1, 1, 1])
    x.zero_()
    np.testing.assert_allclose(x.numpy(), [0, 0, 0])
    x.fill_(7.0)
    np.testing.assert_allclose(x.numpy(), [7, 7, 7])


def test_item_and_scalars():
    x = paddle.to_tensor(3.5)
    assert float(x) == 3.5
    assert x.item() == 3.5
    with pytest.raises(ValueError):
        bool(paddle.ones([2]))


def test_set_value_and_clone():
    x = paddle.ones([2, 2])
    y = x.clone()
    x.set_value(np.zeros([2, 2], np.float32))
    np.testing.assert_allclose(x.numpy(), 0)
    np.testing.assert_allclose(y.numpy(), 1)


def test_creation_ops():
    np.testing.assert_allclose(paddle.zeros([2, 3]).numpy(), np.zeros([2, 3]))
    np.testing.assert_allclose(paddle.full([2], 5.0).numpy(), [5, 5])
    np.testing.assert_allclose(paddle.arange(0, 10, 2).numpy(), [0, 2, 4, 6, 8])
    np.testing.assert_allclose(paddle.linspace(0, 1, 5).numpy(),
                               [0, 0.25, 0.5, 0.75, 1.0])
    e = paddle.eye(3)
    np.testing.assert_allclose(e.numpy(), np.eye(3))
    t = paddle.tril(paddle.ones([3, 3]))
    np.testing.assert_allclose(t.numpy(), np.tril(np.ones([3, 3])))


def test_random_deterministic_given_seed():
    paddle.seed(7)
    a = paddle.randn([4])
    paddle.seed(7)
    b = paddle.randn([4])
    np.testing.assert_allclose(a.numpy(), b.numpy())
    r = paddle.uniform([1000], min=0.0, max=1.0)
    assert 0.0 <= float(r.min()) and float(r.max()) <= 1.0
    p = paddle.randperm(10)
    assert sorted(p.tolist()) == list(range(10))


def test_rng_state_roundtrip():
    paddle.seed(3)
    _ = paddle.randn([2])
    st = paddle.get_rng_state()
    a = paddle.randn([2])
    paddle.set_rng_state(st)
    b = paddle.randn([2])
    np.testing.assert_allclose(a.numpy(), b.numpy())


def test_round4_long_tail_surface():
    """Module in-place aliases, rfloordiv/dlpack dunders, tril/triu
    methods, bernoulli_, set_printoptions."""
    import numpy as np
    import paddle_tpu as paddle

    t = paddle.to_tensor(np.full((2, 2), 7.0, np.float32))
    np.testing.assert_allclose((15 // t).numpy(), 2.0)
    np.testing.assert_allclose(np.from_dlpack(t), 7.0)

    m = paddle.to_tensor(np.ones((3, 3), np.float32))
    np.testing.assert_allclose(m.tril().numpy(),
                               np.tril(np.ones((3, 3))))
    np.testing.assert_allclose(m.triu().numpy(),
                               np.triu(np.ones((3, 3))))
    paddle.tril_(m)
    assert m.numpy()[0, 2] == 0.0 and m.numpy()[2, 0] == 1.0

    paddle.seed(9)
    x = paddle.to_tensor(np.zeros((2000,), np.float32))
    paddle.bernoulli_(x, 0.3)
    assert 0.2 < float(x.numpy().mean()) < 0.4
    assert set(np.unique(x.numpy())) <= {0.0, 1.0}

    y = paddle.to_tensor(np.zeros((4,), np.float32))
    paddle.normal_(y, mean=2.0, std=0.0)
    np.testing.assert_allclose(y.numpy(), 2.0)

    s = paddle.to_tensor(np.zeros((3, 2), np.float32))
    idx = paddle.to_tensor(np.array([1, 0]))
    upd = paddle.to_tensor(np.ones((2, 2), np.float32))
    paddle.scatter_(s, idx, upd)
    np.testing.assert_allclose(s.numpy()[[0, 1]], 1.0)

    try:
        paddle.set_printoptions(precision=2, sci_mode=True)
        assert "e+" in repr(np.array([1.5]))
        paddle.set_printoptions(linewidth=120)   # must keep sci_mode
        assert "e+" in repr(np.array([1.5]))
    finally:
        paddle._printoptions_state.clear()
        np.set_printoptions(precision=8, suppress=False, formatter=None)
