"""Elastic shrink/regrow chaos matrix (ISSUE 6 acceptance) plus the
checkpoint-hygiene / deterministic-resume satellites.

Acceptance: under the dp-4 thread-rank simulator a FaultPlan kills a
rank mid-run; survivors detect it (structured RankFailure — no hang, no
leaked overlap lanes), shrink to dp-3, restore the latest complete
checkpoint, and the post-resume loss trajectory is BIT-identical to a
fresh from-checkpoint restart on 3 ranks at the same step. A delay-only
fault produces a straggler report and no shrink.
"""
import os
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import nn
from paddle_tpu.distributed import fault
from paddle_tpu.distributed.fault import elastic_telemetry
from paddle_tpu.distributed.fleet.elastic import (
    CheckpointManager, ElasticTrainLoop, ElasticWorld, MemKVStore,
)
from paddle_tpu.profiler import flight_recorder as fr


@pytest.fixture(autouse=True)
def _clean_fault_plan():
    fault.clear()
    yield
    fault.clear()


# ---------------------------------------------------------------------------
# harness
# ---------------------------------------------------------------------------

_STEPS = 24


def _build():
    net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 4))
    wr = np.random.default_rng(0)
    for p in net.parameters():
        p.set_value(paddle.to_tensor(
            (wr.normal(size=p.shape) * 0.1).astype(np.float32)))
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=net.parameters())
    return net, opt, nn.MSELoss()


_rng = np.random.default_rng(7)
_X = _rng.normal(size=(_STEPS + 8, 12, 8)).astype(np.float32)
_W = _rng.normal(size=(8, 4)).astype(np.float32)


def _data(step):
    # global batch of 12 rows: splits evenly over 4 AND 3 ranks
    return _X[step], (_X[step] @ _W).astype(np.float32)


def _run_world(ckpt_dir, nprocs, total_steps, plan=None, ckpt_interval=3,
               job_id="job", restore_step=None, sharded=False,
               rejoin_after=None, ttl=1.0):
    """Spawn an elastic dp-N run; returns per-rank result dicts."""
    store = MemKVStore()
    if plan:
        fault.install(plan)

    def worker():
        r = dist.get_rank()
        loop = ElasticTrainLoop(str(ckpt_dir), store=store, job_id=job_id,
                                ckpt_interval=ckpt_interval, ttl=ttl,
                                barrier_timeout=60.0,
                                sharded_checkpoint=sharded)
        res = loop.run(_build, _data, total_steps,
                       restore_step=restore_step)
        if res["status"] == "killed" and rejoin_after is not None:
            # regrow: wait until every survivor has advanced past the
            # shrink, then rejoin through the same loop
            ew = ElasticWorld(store, job_id, rank=r, ttl=ttl)
            deadline = time.monotonic() + 45
            while time.monotonic() < deadline:
                alive = [v for k, v in ew.progress().items() if k != r]
                if alive and min(alive) >= rejoin_after:
                    break
                time.sleep(0.05)
            res = loop.run(_build, _data, total_steps)
            res["rejoined"] = True
        return res

    try:
        return dist.spawn(worker, nprocs=nprocs).results
    finally:
        fault.clear()


def _overlap_threads():
    return {t.ident: t.name for t in threading.enumerate()
            if t.name.startswith("comm-overlap:")}


def _lane_snapshot():
    """Idents of overlap lanes alive right now — earlier test files park
    idle lanes (their schedulers are never closed), so leak checks must
    be DELTAS against this baseline, not absolute."""
    return set(_overlap_threads())


def _assert_no_leaked_lanes(baseline=frozenset()):
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        new = {i: n for i, n in _overlap_threads().items()
               if i not in baseline}
        if not new:
            return
        time.sleep(0.05)
    raise AssertionError(f"leaked overlap lanes: {sorted(new.values())}")


# ---------------------------------------------------------------------------
# chaos matrix
# ---------------------------------------------------------------------------


class TestKillAtStep:
    def test_shrink_and_bit_match_fresh_restart(self, tmp_path):
        """THE acceptance test: kill rank 2 at step 5; survivors shrink
        to [0, 1, 3], restore the step-3 checkpoint, and every step >= 3
        of the post-resume trajectory bit-matches a fresh 3-rank restart
        from the same checkpoint, position for position."""
        ck = tmp_path / "ck"
        base = _lane_snapshot()
        res = _run_world(ck, 4, 10, plan="kill:rank=2,step=5",
                         job_id="kill-step")
        by_rank = {r["rank"]: r for r in res}
        assert by_rank[2]["status"] == "killed"
        survivors = [by_rank[r] for r in (0, 1, 3)]
        for s in survivors:
            assert s["status"] == "done"
            assert s["world"] == [0, 1, 3]
            assert sorted(s["losses"]) == list(range(10))
        _assert_no_leaked_lanes(base)

        # fresh from-checkpoint restart on 3 ranks at the same step
        fresh = _run_world(ck, 3, 10, job_id="fresh", ckpt_interval=1000,
                           restore_step=3)
        fresh.sort(key=lambda r: r["rank"])
        for pos in range(3):
            a = survivors[pos]["losses"]
            b = fresh[pos]["losses"]
            for s in range(3, 10):
                assert a[s] == b[s], (pos, s, a[s], b[s])

    def test_kill_counts_and_events(self, tmp_path):
        c = elastic_telemetry()["events"]
        before = {k: c.value(kind=k)
                  for k in ("kill", "failure_detected", "shrink", "restore")}
        _run_world(tmp_path / "ck", 4, 8, plan="kill:rank=1,step=4",
                   job_id="kill-tel")
        assert c.value(kind="kill") == before["kill"] + 1
        assert c.value(kind="failure_detected") > before["failure_detected"]
        assert c.value(kind="shrink") > before["shrink"]
        assert c.value(kind="restore") > before["restore"]


class TestKillMidCollective:
    def test_seq_kill_shrinks_without_hang_or_leak(self, tmp_path):
        """Kill rank 2 before one of its collectives (mid-backward, on an
        overlap lane): survivors get RankFailure out of the scheduler's
        finish(), release their lanes, shrink and finish."""
        base = _lane_snapshot()
        t0 = time.monotonic()
        res = _run_world(tmp_path / "ck", 4, 10,
                         plan="kill:rank=2,seq=9", job_id="kill-seq")
        assert time.monotonic() - t0 < 120       # detection, not timeout
        by_rank = {r["rank"]: r for r in res}
        assert by_rank[2]["status"] == "killed"
        for r in (0, 1, 3):
            assert by_rank[r]["status"] == "done"
            assert by_rank[r]["world"] == [0, 1, 3]
            assert sorted(by_rank[r]["losses"]) == list(range(10))
        _assert_no_leaked_lanes(base)


class TestKillDuringCheckpoint:
    def test_writer_death_leaves_no_tmp_and_survivors_resume(self, tmp_path):
        """Kill the checkpoint WRITER (world position 0 = rank 0) on the
        step right after a checkpoint boundary. Survivors must restore a
        COMPLETE checkpoint (the atomic rename guarantees no torn read)
        and the rebuild barrier's orphan sweep must leave no step_*.tmp
        behind."""
        ck = tmp_path / "ck"
        res = _run_world(ck, 4, 10, plan="kill:rank=0,step=4",
                         job_id="kill-writer", ckpt_interval=2)
        by_rank = {r["rank"]: r for r in res}
        assert by_rank[0]["status"] == "killed"
        for r in (1, 2, 3):
            assert by_rank[r]["status"] == "done"
            assert by_rank[r]["world"] == [1, 2, 3]
            assert sorted(by_rank[r]["losses"]) == list(range(10))
        leftovers = [n for n in os.listdir(ck) if n.endswith(".tmp")]
        assert not leftovers, leftovers
        assert CheckpointManager(str(ck)).steps()       # checkpoints exist

    def test_sharded_checkpoint_mode_shrinks_too(self, tmp_path):
        """Same chaos with sharded (distributed.checkpoint) async saves:
        restore-and-reshard onto the smaller world rides the
        re-shard-on-load path."""
        ck = tmp_path / "ck"
        res = _run_world(ck, 4, 10, plan="kill:rank=1,step=5",
                         job_id="kill-sharded", sharded=True)
        by_rank = {r["rank"]: r for r in res}
        assert by_rank[1]["status"] == "killed"
        for r in (0, 2, 3):
            assert by_rank[r]["status"] == "done"
            assert by_rank[r]["world"] == [0, 2, 3]
        steps = CheckpointManager(str(ck)).steps()
        assert steps
        assert os.path.exists(os.path.join(ck, f"step_{steps[-1]}",
                                           "metadata.json"))


class TestSlowRank:
    def test_delay_only_reports_straggler_no_shrink(self, tmp_path):
        """A 0.5 s delay on rank 3 is a straggler, not a failure: the
        world must NOT shrink, and the flight recorder's straggler
        report must name rank 3."""
        fr.reset()
        fr.enable()
        c = elastic_telemetry()["events"]
        shrinks0 = c.value(kind="shrink")
        try:
            res = _run_world(tmp_path / "ck", 4, 8,
                             plan="delay:rank=3,step=4,seconds=0.5",
                             job_id="slow", ttl=5.0)
            by_rank = {r["rank"]: r for r in res}
            for r in range(4):
                assert by_rank[r]["status"] == "done"
                assert by_rank[r]["world"] == [0, 1, 2, 3]
            assert c.value(kind="shrink") == shrinks0
            rep = fr.straggler_report(
                fr.get_flight_recorder().collective_events(by_rank=True))
            assert rep["slowest_rank"] == 3
            assert rep["per_rank_lag"][3]["max_s"] >= 0.2
        finally:
            fr.disable()
            fr.reset()


class TestRegrow:
    def test_killed_rank_readmitted_at_checkpoint_boundary(self, tmp_path):
        base = _lane_snapshot()
        c = elastic_telemetry()["events"]
        regrow0 = c.value(kind="regrow")
        res = _run_world(tmp_path / "ck", 4, 20,
                         plan="kill:rank=2,step=5", job_id="regrow",
                         ckpt_interval=2, rejoin_after=10)
        by_rank = {r["rank"]: r for r in res}
        assert by_rank[2].get("rejoined") is True
        for r in range(4):
            assert by_rank[r]["status"] == "done"
            assert by_rank[r]["world"] == [0, 1, 2, 3]   # regrown world
            assert max(by_rank[r]["losses"]) == 19
        assert c.value(kind="regrow") > regrow0
        # the rejoiner resumed from a checkpoint, not from step 0
        assert min(by_rank[2]["losses"]) >= 2
        _assert_no_leaked_lanes(base)


# ---------------------------------------------------------------------------
# satellites: checkpoint hygiene, atomic io.save, overlap-timeout
# diagnosis, DataLoader deterministic resume
# ---------------------------------------------------------------------------


class TestCheckpointHygiene:
    def test_retention_sweeps_stale_orphan_tmp(self, tmp_path):
        cm = CheckpointManager(str(tmp_path), keep=2)
        orphan = tmp_path / "step_5.tmp"
        orphan.mkdir()
        (orphan / "state.pdz").write_bytes(b"torn")
        cm.save(10, {"w": paddle.to_tensor(np.ones(3, np.float32))})
        assert not orphan.exists()          # swept: 5 <= newest complete 10
        assert cm.steps() == [10]

    def test_sweep_orphans_removes_everything_staged(self, tmp_path):
        cm = CheckpointManager(str(tmp_path))
        (tmp_path / "step_99.tmp").mkdir()
        removed = cm.sweep_orphans()
        assert removed == ["step_99.tmp"]
        assert not (tmp_path / "step_99.tmp").exists()

    def test_resave_over_complete_checkpoint(self, tmp_path):
        # a run restored from an earlier step re-writes later steps:
        # publishing over an existing COMPLETE step dir must not
        # ENOTEMPTY (os.replace can't overwrite a non-empty directory)
        cm = CheckpointManager(str(tmp_path))
        cm.save(6, {"w": paddle.to_tensor(np.zeros(3, np.float32))})
        cm.save(6, {"w": paddle.to_tensor(np.ones(3, np.float32))})
        step, state = cm.load()
        assert step == 6
        np.testing.assert_allclose(state["w"].numpy(), 1.0)
        assert not [n for n in os.listdir(tmp_path) if n.endswith(".tmp")]

    def test_save_async_is_durable_and_counted(self, tmp_path):
        h = elastic_telemetry()["ckpt_async"]
        n0 = h.labels().count
        cm = CheckpointManager(str(tmp_path))
        handle = cm.save_async(3, {"w": paddle.to_tensor(
            np.arange(4, dtype=np.float32))})
        handle.wait()
        step, state = cm.load()
        assert step == 3
        np.testing.assert_array_equal(state["w"].numpy(),
                                      np.arange(4, dtype=np.float32))
        assert h.labels().count == n0 + 1

    def test_load_waits_pending_async_save(self, tmp_path):
        cm = CheckpointManager(str(tmp_path))
        cm.save_async(7, {"w": paddle.to_tensor(np.full(2, 7, np.float32))})
        step, state = cm.load()             # no explicit wait
        assert step == 7
        np.testing.assert_allclose(state["w"].numpy(), 7.0)

    def test_sharded_roundtrip_reuses_reshard_on_load(self, tmp_path):
        cm = CheckpointManager(str(tmp_path))
        src = {"model": {"w": paddle.to_tensor(
            np.arange(12, dtype=np.float32).reshape(3, 4))}, "step": 4}
        cm.save_sharded(4, src)
        tmpl = {"model": {"w": paddle.to_tensor(
            np.zeros((3, 4), np.float32))}, "step": 0}
        step, loaded = cm.load_sharded(tmpl)
        assert step == 4
        np.testing.assert_array_equal(
            loaded["model"]["w"].numpy(),
            np.arange(12, dtype=np.float32).reshape(3, 4))

    def test_pickle_load_rejects_sharded_dir(self, tmp_path):
        cm = CheckpointManager(str(tmp_path))
        cm.save_sharded(2, {"w": paddle.to_tensor(np.ones(2, np.float32))})
        with pytest.raises(ValueError, match="load_sharded"):
            cm.load()


class TestAtomicIoSave:
    def test_failed_save_leaves_no_partial_target(self, tmp_path):
        from paddle_tpu.framework import io as fio
        path = tmp_path / "state.pdz"
        fio.save({"ok": paddle.to_tensor(np.ones(2, np.float32))}, str(path))
        good = path.read_bytes()

        class Unpicklable:
            def __reduce__(self):
                raise RuntimeError("cannot pickle me")

        with pytest.raises(Exception):
            fio.save({"bad": Unpicklable()}, str(path))
        # target untouched, no tmp litter
        assert path.read_bytes() == good
        assert [n for n in os.listdir(tmp_path) if ".tmp" in n] == []

    def test_save_then_load_roundtrip(self, tmp_path):
        from paddle_tpu.framework import io as fio
        path = str(tmp_path / "x.pdz")
        fio.save({"a": paddle.to_tensor(np.eye(3, dtype=np.float32))}, path)
        out = fio.load(path)
        np.testing.assert_array_equal(out["a"].numpy(), np.eye(3))


class TestOverlapTimeoutDiagnosis:
    def test_timeout_carries_desync_report_and_releases_lanes(self):
        """Rank 1 skips its step: rank 0's in-flight bucket can never
        pair. The TimeoutError must (a) arrive within the bound, (b)
        carry the flight-recorder desync report naming the rank/seq that
        never entered, (c) leave no _RankWorker lanes behind."""
        base = _lane_snapshot()
        os.environ["PADDLE_COMM_OVERLAP_TIMEOUT_S"] = "3"
        fr.reset()
        fr.enable()
        try:
            def worker():
                r = dist.get_rank()
                model = nn.Linear(8, 4)
                model.weight.set_value(paddle.to_tensor(
                    np.ones((8, 4), np.float32) * 0.1))
                strat = dist.fleet.DistributedStrategy()
                strat.hybrid_configs = {"dp_degree": 2}
                strat.comm_overlap = True
                opt = dist.fleet.HybridParallelOptimizer(
                    paddle.optimizer.SGD(learning_rate=0.1,
                                         parameters=model.parameters()),
                    strategy=strat)
                if r == 1:
                    return "skipped"
                x = paddle.to_tensor(np.ones((2, 8), np.float32))
                model(x).sum().backward()
                opt.step()
                return "stepped"

            with pytest.raises(RuntimeError) as ei:
                dist.spawn(worker, nprocs=2)
            msg = str(ei.value)
            assert "did not complete" in msg
            assert "desync report" in msg
            assert "never entered" in msg
            _assert_no_leaked_lanes(base)
        finally:
            os.environ.pop("PADDLE_COMM_OVERLAP_TIMEOUT_S", None)
            fr.disable()
            fr.reset()


class _Rows(paddle.io.Dataset):
    def __init__(self, n=20):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return np.full((2,), i, np.float32)


class TestDataLoaderResume:
    @staticmethod
    def _ids(batches):
        return [sorted(int(v) for v in np.asarray(b.numpy())[:, 0])
                for b in batches]

    def test_seeded_shuffle_resume_skips_exactly_consumed(self):
        loader = paddle.io.DataLoader(_Rows(), batch_size=4, shuffle=True,
                                      seed=11, num_workers=0)
        it = iter(loader)
        consumed = [next(it) for _ in range(2)]
        state = loader.state_dict()
        assert state["consumed_batches"] == 2 and state["seed"] == 11
        # abandon mid-epoch; a NEW loader resumes from the state
        resumed = paddle.io.DataLoader(_Rows(), batch_size=4, shuffle=True,
                                       seed=11, num_workers=0)
        resumed.set_state_dict(state)
        rest = list(resumed)
        # reference: the full epoch order is a pure fn of (seed, epoch)
        full = list(paddle.io.DataLoader(_Rows(), batch_size=4, shuffle=True,
                                         seed=11, num_workers=0))
        assert self._ids(consumed) + self._ids(rest) == self._ids(full)
        assert len(rest) == 3

    def test_resume_epoch_keeps_shuffle_order(self):
        a = paddle.io.DataLoader(_Rows(), batch_size=5, shuffle=True, seed=3)
        a.batch_sampler.set_epoch(2)
        order_a = self._ids(list(a))
        b = paddle.io.DataLoader(_Rows(), batch_size=5, shuffle=True, seed=3)
        b.set_state_dict({"epoch": 2, "consumed_batches": 0, "seed": 3})
        assert self._ids(list(b)) == order_a
        # different epoch -> different order
        c = paddle.io.DataLoader(_Rows(), batch_size=5, shuffle=True, seed=3)
        c.batch_sampler.set_epoch(3)
        assert self._ids(list(c)) != order_a

    def test_unseeded_shuffle_resume_rejected(self):
        loader = paddle.io.DataLoader(_Rows(), batch_size=4, shuffle=True)
        loader.set_state_dict({"epoch": 0, "consumed_batches": 2})
        with pytest.raises(ValueError, match="needs a seed"):
            list(loader)

    def test_next_epoch_after_resume_is_fresh(self):
        loader = paddle.io.DataLoader(_Rows(12), batch_size=4, shuffle=True,
                                      seed=5)
        loader.set_state_dict({"epoch": 0, "consumed_batches": 1, "seed": 5})
        assert len(list(loader)) == 2       # skipped one
        assert len(list(loader)) == 3       # fresh epoch, no skip

    def test_overskip_raises(self):
        loader = paddle.io.DataLoader(_Rows(8), batch_size=4, shuffle=True,
                                      seed=5)
        loader.set_state_dict({"epoch": 0, "consumed_batches": 9, "seed": 5})
        with pytest.raises(ValueError, match="only"):
            list(loader)
