"""Per-request distributed tracing + SLO monitor (ISSUE 9): trace-store
unit tier, SLO percentile/goodput accounting, engine + fleet span
wiring, disagg trace continuity, kill-mid-decode requeue attempts under
one trace_id, chrome flow rendering, the trace_merge --request CLI, and
bit-parity with tracing disabled."""
import importlib.util
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.fleet.elastic.tcp_kv import MemKVStore
from paddle_tpu.inference import Rejected, ServingRouter
from paddle_tpu.inference import ContinuousServingEngine
from paddle_tpu.models import LlamaForCausalLM, llama_tiny
from paddle_tpu.profiler import flight_recorder
from paddle_tpu.profiler import request_trace as rt
from paddle_tpu.profiler.request_trace import _exact_percentile

ENGINE_KW = dict(max_batch_size=4, max_len=160, page_size=16,
                 prefill_chunk_tokens=32)


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    return LlamaForCausalLM(llama_tiny(num_hidden_layers=1,
                                       max_position_embeddings=256))


@pytest.fixture(autouse=True)
def fresh_trace_state():
    rt.enable()
    rt.get_trace_store().clear()
    rt.reset_slo_monitor()
    yield
    rt.enable()


def _oracle(model, p, n):
    return np.asarray(model.generate(paddle.to_tensor(p),
                                     max_new_tokens=n)._data)


def _mixed_workload(n_req=8, sys_len=48, tail=8, seed=0):
    rng = np.random.RandomState(seed)
    sys_prompt = rng.randint(0, 128, sys_len)
    prompts = [np.concatenate([sys_prompt, rng.randint(0, 128, tail)])
               .astype(np.int64)[None] for _ in range(n_req)]
    return prompts


def _records_by_tenant():
    store = rt.get_trace_store()
    out = {}
    for tid in store.trace_ids():
        rec = store.timeline(tid)
        out.setdefault(rec["tenant"], []).append(rec)
    return out


def _first_t0(rec, *names):
    for s in rec["spans"]:
        if s["name"] in names:
            return s["t0"]
    return None


# ---------------------------------------------------------------------------
# unit tier: store, SLO monitor, cost table
# ---------------------------------------------------------------------------

def test_store_lifecycle_and_timeline():
    ctx = rt.start_request(tenant="acme", source="router",
                           prompt_tokens=40, max_new_tokens=4)
    assert ctx is not None and ctx.trace_id
    rt.add_span(ctx, "queue_wait", t0=ctx.t0, dur=0.25)
    ctx.set_tags(replica="r0", attempt=1)
    rt.add_event(ctx, "admit", cached_tokens=32)
    base = time.perf_counter()
    for i in range(4):
        rt.note_token(ctx, base + 0.1 * (i + 1))
    ctx.set_tags(replica="r1", attempt=2)
    rt.add_event(ctx, "requeue", reason="replica_dead")
    rec = rt.finish_request(ctx, status="ok")
    assert rec["status"] == "ok"
    s = rec["summary"]
    assert s["queue_wait_s"] == pytest.approx(0.25)
    assert s["tokens_generated"] == 4
    assert s["tpot_s"] == pytest.approx(0.1, abs=1e-6)
    assert s["cached_tokens"] == 32
    assert s["replica_hops"] == ["r0", "r1"]
    assert s["requeues"] == 1 and s["attempts"] == 2
    # the facade returns the same record; double-finish never overwrites
    tl = rt.request_timeline(ctx.trace_id)
    assert tl["status"] == "ok"
    rt.finish_request(ctx, status="error")
    assert rt.request_timeline(ctx.trace_id)["status"] == "ok"
    # spans are rank-stamped and ordered
    assert all("rank" in sp for sp in tl["spans"])
    names = [sp["name"] for sp in tl["spans"]]
    assert names[0] == "queue_wait" and names[-1] == "done"


def test_store_eviction_prefers_finished():
    store = rt.RequestTraceStore(capacity=8)
    open_ctx = store.start(tenant="keep")
    done_ids = []
    for i in range(10):
        c = store.start(tenant=f"t{i}")
        store.finish(c, status="ok")
        done_ids.append(c.trace_id)
    ids = store.trace_ids()
    assert len(ids) <= 8
    assert open_ctx.trace_id in ids     # open records evict last


def test_disabled_layer_is_inert():
    rt.disable()
    assert rt.start_request(tenant="x") is None
    assert rt.add_span(None, "y") is None
    rt.note_token(None)
    assert rt.finish_request(None) is None
    assert rt.get_trace_store().trace_ids() == []


def test_slo_monitor_exact_percentiles_and_goodput():
    mon = rt.SLOMonitor(window=100, ttft_ms=50.0, tpot_ms=10.0)
    ttfts = [0.01 * (i + 1) for i in range(10)]       # 10ms .. 100ms
    for v in ttfts:
        mon.observe(ttft_s=v, tpot_s=0.005, queue_wait_s=0.001)
    rep = mon.report()
    sv = sorted(ttfts)
    for q in (50, 95, 99):
        assert rep["ttft"][f"p{q}_s"] == _exact_percentile(sv, q)
    # 5 of 10 TTFTs exceed the 50ms target; every TPOT is inside 10ms
    assert rep["violations"]["ttft"] == 5
    assert rep["goodput"]["ttft"] == 5
    assert rep["goodput"]["tpot"] == 10
    assert rep["violations"]["request"] == 5
    assert rep["goodput_ratio"] == pytest.approx(0.5)
    mon.reset()
    assert mon.report()["ttft"]["count"] == 0


def test_slo_env_targets_and_gauges(monkeypatch):
    monkeypatch.setenv("PADDLE_SLO_TTFT_MS", "20")
    monkeypatch.setenv("PADDLE_SLO_WINDOW", "4")
    mon = rt.reset_slo_monitor()
    assert mon.targets_s["ttft"] == pytest.approx(0.02)
    assert mon.window == 4
    for v in (0.01, 0.03):
        mon.observe(ttft_s=v)
    from paddle_tpu.profiler.telemetry import get_registry
    g = get_registry().get("paddle_slo_latency_seconds")
    assert g.value(metric="ttft", quantile="p95") == pytest.approx(0.03)
    snap = get_registry().collect()
    assert "paddle_slo_goodput_total" in snap
    assert "paddle_slo_violations_total" in snap


def test_cost_table_folds_collectives_programs_slo():
    flight_recorder.enable()
    try:
        ev = flight_recorder.collective_begin("all_reduce", 1 << 20,
                                              [0, 1])
        time.sleep(0.01)
        flight_recorder.collective_end(ev)
        from paddle_tpu.profiler.telemetry import get_registry
        h = get_registry().histogram("paddle_test_cost_seconds",
                                     "cost-table probe")
        h.observe(0.125)
        rt.get_slo_monitor().observe(ttft_s=0.2, tpot_s=0.01)
        table = rt.cost_table()
    finally:
        flight_recorder.disable()
    # schema v2 (ISSUE 12): adds the training phases/memory sections
    assert table["schema"] == "paddle_cost_table/2"
    assert "phases" in table and "memory" in table
    ar = table["collectives"]["all_reduce"]
    assert ar["calls"] >= 1 and ar["bytes"] >= 1 << 20
    assert ar["bytes_per_s"] > 0
    probe = table["programs"]["paddle_test_cost_seconds"]
    assert probe["count"] >= 1 and probe["mean_s"] > 0
    assert table["slo"]["ttft"]["count"] >= 1
    assert "sim_gbps" in table["wire_model"]
    assert "comm" in table


# ---------------------------------------------------------------------------
# engine tier: spans through the continuous scheduler
# ---------------------------------------------------------------------------

def test_engine_trace_spans_and_timeline(model):
    p = _mixed_workload(n_req=1)[0]
    eng = ContinuousServingEngine(model, **ENGINE_KW)
    with eng:
        eng.generate(p, max_new_tokens=4, timeout=600)
    ids = rt.get_trace_store().trace_ids()
    assert len(ids) == 1
    tl = rt.request_timeline(ids[0])
    assert tl["status"] == "ok" and tl["source"] == "continuous"
    names = [s["name"] for s in tl["spans"]]
    for need in ("queue_wait", "admit", "prefill_chunk", "decode", "done"):
        assert need in names, names
    # lifecycle edges in monotonic order
    t_q = _first_t0(tl, "queue_wait")
    t_p = _first_t0(tl, "prefill_chunk")
    t_d = _first_t0(tl, "decode")
    t_done = _first_t0(tl, "done")
    assert t_q <= t_p <= t_d <= t_done
    assert tl["summary"]["tokens_generated"] == 4
    assert tl["summary"]["ttft_s"] > 0
    # the completed request fed the SLO window
    assert rt.slo_report()["ttft"]["count"] == 1


def test_engine_state_names_oldest_request(model):
    from paddle_tpu.inference.serving import _engine_state
    eng = ContinuousServingEngine(model, **ENGINE_KW)
    p = _mixed_workload(n_req=1)[0]
    hold = threading.Event()
    with eng:
        blocker = threading.Thread(
            target=lambda: eng.run_on_loop(lambda e: hold.wait(15),
                                           timeout=30), daemon=True)
        blocker.start()
        time.sleep(0.05)        # the control is on the loop: ticks frozen
        t = threading.Thread(
            target=lambda: eng.generate(p, max_new_tokens=2, timeout=600))
        t.start()
        deadline = time.monotonic() + 5
        state = {}
        while time.monotonic() < deadline:
            state = _engine_state(eng)
            if state.get("oldest_request_age_s", 0) > 0:
                break
            time.sleep(0.01)
        assert state.get("oldest_request_age_s", 0) > 0, state
        assert state["oldest_request_trace"], state
        assert state["request_ages"][0]["state"] == "queued"
        hold.set()
        t.join()
    # after completion the engine reports no stuck request
    assert _engine_state(eng)["oldest_request_age_s"] == 0.0


def test_trace_disabled_bit_parity(model):
    p = _mixed_workload(n_req=1, seed=3)[0]
    want = _oracle(model, p, 3)
    eng = ContinuousServingEngine(model, **ENGINE_KW)
    with eng:
        traced = np.asarray(eng.generate(p, max_new_tokens=3,
                                         timeout=600).numpy())
    rt.disable()
    n_before = len(rt.get_trace_store().trace_ids())
    eng2 = ContinuousServingEngine(model, **ENGINE_KW)
    with eng2:
        untraced = np.asarray(eng2.generate(p, max_new_tokens=3,
                                            timeout=600).numpy())
    rt.enable()
    np.testing.assert_array_equal(traced, want)
    np.testing.assert_array_equal(untraced, want)     # bit-identical
    assert len(rt.get_trace_store().trace_ids()) == n_before


def test_watchdog_dump_carries_request_timelines(model, tmp_path):
    p = _mixed_workload(n_req=1, seed=5)[0]
    eng = ContinuousServingEngine(model, **ENGINE_KW)
    with eng:
        eng.generate(p, max_new_tokens=2, timeout=600)
    out = flight_recorder.get_flight_recorder().dump(
        reason="test", directory=str(tmp_path))
    path = next(iter(out["ranks"].values()))
    with open(path) as f:
        dump = json.load(f)
    traces = dump["state"]["request_traces"]
    assert traces["recent"], traces
    assert traces["recent"][0]["trace_id"].startswith("req-")
    assert "summary" in traces["recent"][0]


# ---------------------------------------------------------------------------
# fleet acceptance: 2-replica disagg, >=8 mixed-tenant requests, one
# rejected + one requeued after a hard kill — one trace each, ordered
# spans, chrome flow, SLO p95 == raw timelines, parity
# ---------------------------------------------------------------------------

def test_fleet_acceptance_disagg_request_tracing(model):
    n_req = 8
    prompts = _mixed_workload(n_req=n_req)
    want = [_oracle(model, p, 3) for p in prompts]
    router = ServingRouter(
        model, num_replicas=2, disagg=True, engine_kwargs=ENGINE_KW,
        store=MemKVStore(), heartbeat_ttl=60.0,
        tenant_quotas={"blocked": (8, 0.0)})   # below any request cost
    results = [None] * n_req
    errors = [None] * n_req

    def call(i):
        try:
            results[i] = np.asarray(router.generate(
                prompts[i], max_new_tokens=3, tenant=f"t{i}",
                timeout=600).numpy())
        except Exception as e:          # noqa: BLE001 — asserted below
            errors[i] = e

    with router:
        # (1) a rejected request must trace too
        with pytest.raises(Rejected):
            router.generate(prompts[0], max_new_tokens=3,
                            tenant="blocked", timeout=600)
        # (2) warm request: full prefill->handoff->decode flow, no chaos
        call(0)
        # (3) concurrent batch with the prefill replica hard-killed while
        # one request is provably in flight on it (loop frozen by a
        # control, so the kill cannot race past the attempt)
        pre = router._replica("r0")
        assert pre.role == "prefill"
        hold = threading.Event()
        blocker = threading.Thread(
            target=lambda: pre.engine.run_on_loop(
                lambda e: hold.wait(20), timeout=60), daemon=True)
        blocker.start()
        time.sleep(0.05)
        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(1, n_req)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 5
        while not pre.inflight and time.monotonic() < deadline:
            time.sleep(0.01)
        assert pre.inflight, "no in-flight work on the prefill replica"
        router.kill_replica("r0")
        hold.set()
        for t in threads:
            t.join()
        stats = router.stats()
    assert not [e for e in errors if e], errors
    for g, w in zip(results, want):                      # bit-parity
        np.testing.assert_array_equal(g, w)
    assert stats["requeues_total"] >= 1, stats

    recs = _records_by_tenant()
    # every request (served, rejected, requeued) has exactly ONE trace
    assert len(recs["blocked"]) == 1
    rejected = recs["blocked"][0]
    assert rejected["status"] == "rejected"
    names = [s["name"] for s in rejected["spans"]]
    assert "admission" in names and "rejected" in names
    tags = next(s for s in rejected["spans"]
                if s["name"] == "rejected")["tags"]
    assert tags["reason"] == "tenant_quota"

    requeued = 0
    for i in range(n_req):
        assert len(recs[f"t{i}"]) == 1, f"t{i} traced more than once"
        rec = recs[f"t{i}"][0]
        assert rec["status"] == "ok"
        names = [s["name"] for s in rec["spans"]]
        for need in ("admission", "route", "prefill_chunk", "decode",
                     "done"):
            assert need in names, (i, names)
        assert any(n.startswith("handoff") for n in names), (i, names)
        # monotonic lifecycle edges
        t_adm = _first_t0(rec, "admission")
        t_route = _first_t0(rec, "route")
        t_pre = _first_t0(rec, "prefill_chunk")
        t_dec = _first_t0(rec, "decode")
        t_done = _first_t0(rec, "done")
        t_hand = _first_t0(rec, "handoff_export", "handoff_import",
                           "handoff", "handoff_skipped")
        assert t_adm <= t_route <= t_pre <= t_dec <= t_done, i
        assert t_adm <= t_hand <= t_done, i
        if any(s["name"] == "requeue" for s in rec["spans"]):
            requeued += 1
            attempts = {s.get("attempt") for s in rec["spans"]}
            assert {1, 2} <= attempts, attempts
    assert requeued >= 1

    # the warm request's flow: prefill on r0, handoff, decode on r1,
    # rendered by merge_chrome_traces as ONE flow keyed by trace_id
    warm = recs["t0"][0]
    assert warm["summary"]["replica_hops"] == ["r0", "r1"]
    t_hand = _first_t0(warm, "handoff_export")
    assert (_first_t0(warm, "prefill_chunk") <= t_hand
            <= _first_t0(warm, "decode"))
    lanes = rt.timeline_to_chrome(warm)
    assert {"router", "r0", "r1"} <= set(lanes)
    merged = flight_recorder.merge_chrome_traces(lanes)
    flow = [e for e in merged["traceEvents"]
            if e.get("cat") == "request" and e["id"] == warm["trace_id"]]
    assert [e["ph"] for e in flow].count("s") == 1
    assert [e["ph"] for e in flow].count("f") == 1
    assert len({e["pid"] for e in flow}) >= 2      # spans >1 lane

    # SLO p95 TTFT == raw per-request timelines (exact, same formula)
    raw = sorted(r[0]["summary"]["ttft_s"]
                 for r in recs.values() if r[0]["status"] == "ok")
    rep = rt.slo_report()
    assert rep["ttft"]["count"] == len(raw)
    assert rep["ttft"]["p95_s"] == pytest.approx(
        _exact_percentile(raw, 95), rel=1e-9)
    # timed-out/rejected requests count in the rejected metric
    from paddle_tpu.profiler.telemetry import get_registry
    c = get_registry().get("paddle_fleet_rejected_total")
    assert c.value(tenant="blocked", reason="tenant_quota") >= 1


def test_fleet_kill_mid_decode_attempt_spans(model):
    """Colocated 2-replica fleet, replica hard-killed mid-decode: the
    request requeues to the survivor and its trace shows attempt-1 AND
    attempt-2 spans under the same trace_id, output still bit-identical."""
    prompts = _mixed_workload(n_req=4, sys_len=32, seed=2)
    want = [_oracle(model, p, 12) for p in prompts]
    router = ServingRouter(model, num_replicas=2, policy="balance",
                           engine_kwargs=ENGINE_KW, store=MemKVStore(),
                           heartbeat_ttl=60.0)
    results = [None] * 4
    errors = [None] * 4

    def call(i):
        try:
            results[i] = np.asarray(router.generate(
                prompts[i], max_new_tokens=12, tenant=f"t{i}",
                timeout=600).numpy())
        except Exception as e:          # noqa: BLE001 — asserted below
            errors[i] = e

    with router:
        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 5
        victim = None
        while time.monotonic() < deadline:
            busy = [r for r in router.replicas if r.inflight]
            if busy:
                victim = max(busy, key=lambda r: len(r.inflight))
                break
            time.sleep(0.01)
        assert victim is not None, "no in-flight work to kill under"
        router.kill_replica(victim.id)
        for t in threads:
            t.join()
        stats = router.stats()
    assert not [e for e in errors if e], errors
    for g, w in zip(results, want):
        np.testing.assert_array_equal(g, w)
    assert stats["requeues_total"] >= 1, stats
    requeued = [rec for recs in _records_by_tenant().values()
                for rec in recs
                if any(s["name"] == "requeue" for s in rec["spans"])]
    assert requeued, "no trace recorded the requeue"
    for rec in requeued:
        assert rec["status"] == "ok"
        attempts = {s.get("attempt") for s in rec["spans"]} - {None}
        assert {1, 2} <= attempts, attempts
        assert rec["summary"]["requeues"] >= 1
        assert len(rec["summary"]["replica_hops"]) >= 1
        # both attempts share the one trace_id by construction: every
        # span above came from the same record
        assert rec["summary"]["attempts"] >= 2


def test_fleet_timeout_traces_and_counts(model):
    """A timed-out fleet request ends its trace (status=timeout) and
    lands in paddle_fleet_rejected_total{reason="timeout"}."""
    p = _mixed_workload(n_req=1, seed=9)[0]
    router = ServingRouter(model, num_replicas=2, engine_kwargs=ENGINE_KW,
                           store=MemKVStore(), heartbeat_ttl=60.0)
    with router:
        hold = threading.Event()
        for r in router.replicas:       # freeze every engine loop
            threading.Thread(
                target=lambda r=r: r.engine.run_on_loop(
                    lambda e: hold.wait(10), timeout=30),
                daemon=True).start()
        time.sleep(0.05)
        with pytest.raises(TimeoutError):
            router.generate(p, max_new_tokens=2, tenant="slow",
                            timeout=0.3)
        hold.set()
    recs = _records_by_tenant()["slow"]
    assert len(recs) == 1
    assert recs[0]["status"] == "timeout"
    assert any(s["name"] == "timeout" for s in recs[0]["spans"])
    from paddle_tpu.profiler.telemetry import get_registry
    c = get_registry().get("paddle_fleet_rejected_total")
    assert c.value(tenant="slow", reason="timeout") >= 1


# ---------------------------------------------------------------------------
# CLI: trace_merge --request
# ---------------------------------------------------------------------------

def _load_trace_merge():
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "trace_merge.py")
    spec = importlib.util.spec_from_file_location("_trace_merge_test",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_trace_merge_request_filter(model, tmp_path):
    p = _mixed_workload(n_req=2, seed=7)
    eng = ContinuousServingEngine(model, **ENGINE_KW)
    with eng:
        eng.generate(p[0], max_new_tokens=2, timeout=600)
        eng.generate(p[1], max_new_tokens=2, timeout=600)
    ids = rt.get_trace_store().trace_ids()
    assert len(ids) == 2
    for i, tid in enumerate(ids):
        with open(tmp_path / f"timeline{i}.json", "w") as f:
            json.dump(rt.request_timeline(tid), f)
    tm = _load_trace_merge()
    out = tmp_path / "one.json"
    rc = tm.main(["--trace", str(out), "--request", ids[0],
                  str(tmp_path / "timeline0.json"),
                  str(tmp_path / "timeline1.json")])
    assert rc == 0
    with open(out) as f:
        merged = json.load(f)
    got_ids = {(e.get("args") or {}).get("trace_id")
               for e in merged["traceEvents"]}
    assert got_ids <= {None, ids[0]}, got_ids
    assert any((e.get("args") or {}).get("trace_id") == ids[0]
               for e in merged["traceEvents"])
    # an unknown trace id is a clean non-zero exit
    rc = tm.main(["--trace", str(tmp_path / "none.json"),
                  "--request", "req-nope",
                  str(tmp_path / "timeline0.json")])
    assert rc == 2
