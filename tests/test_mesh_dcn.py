"""Multi-slice (DCN) mesh topology: device ordering and validation.

Reference analogue: multi-node Fleet keeps comm rings node-local and
crosses nodes only on the dp axis (SURVEY.md §2.3 comm backend — ICI
intra-pod / DCN inter-slice). jax exposes slice membership as
``device.slice_index``; ``init_mesh`` must order devices slice-major and
refuse degree layouts whose inner axes would straddle slices.
"""
import pytest

from paddle_tpu.distributed import mesh as mesh_mod


class FakeDev:
    def __init__(self, id, slice_index):
        self.id = id
        self.slice_index = slice_index

    def __repr__(self):
        return f"d{self.id}@s{self.slice_index}"


def _devs(n, n_slices):
    per = n // n_slices
    # interleaved on purpose — jax.devices() order is not guaranteed
    # slice-contiguous on multi-slice systems
    return [FakeDev(i, i % n_slices) for i in range(n)]


def test_slice_major_groups_contiguously():
    devs = _devs(8, 2)
    ordered, ns = mesh_mod._slice_major(devs)
    assert ns == 2
    assert [d.slice_index for d in ordered] == [0] * 4 + [1] * 4
    # stable within a slice (keeps jax's ICI-friendly enumeration order)
    assert [d.id for d in ordered] == [0, 2, 4, 6, 1, 3, 5, 7]


def test_single_slice_passthrough():
    devs = [FakeDev(i, 0) for i in range(4)]
    ordered, ns = mesh_mod._slice_major(devs)
    assert ns == 1 and [d.id for d in ordered] == [0, 1, 2, 3]


def test_missing_slice_index_treated_as_one_slice():
    class Bare:
        pass
    ordered, ns = mesh_mod._slice_major([Bare(), Bare()])
    assert ns == 1


def test_uneven_slices_rejected():
    devs = [FakeDev(0, 0), FakeDev(1, 0), FakeDev(2, 1)]
    with pytest.raises(ValueError, match="uneven DCN slices"):
        mesh_mod._slice_major(devs)


def test_inner_axis_straddling_rejected():
    saved = mesh_mod._global_mesh
    try:
        # dp=1, mp=8 over 2 slices: mp would cross the DCN boundary
        with pytest.raises(ValueError, match="multiple of the DCN slice"):
            mesh_mod.init_mesh({"dp": 1, "mp": 8}, devices=_devs(8, 2))
    finally:
        mesh_mod._global_mesh = saved


def test_dp_across_slices_allowed():
    saved = mesh_mod._global_mesh
    try:
        m = mesh_mod.init_mesh({"dp": 2, "mp": 4}, devices=_devs(8, 2))
        arr = m.devices
        # dp index 0 -> slice 0, dp index 1 -> slice 1; mp stays intra-slice
        assert all(d.slice_index == 0 for d in arr[0].reshape(-1))
        assert all(d.slice_index == 1 for d in arr[1].reshape(-1))
    finally:
        mesh_mod._global_mesh = saved


def test_cost_model_prices_dcn():
    """A dp group spanning slices must cost more than the same group on
    one slice — the inter-slice leg rides DCN, not ICI."""
    from paddle_tpu.distributed.auto_parallel.cost_model import (CostModel,
                                                                 ModelSpec)
    m = ModelSpec(num_layers=22, hidden=2048, intermediate=5632,
                  vocab=32000, seq_len=2048, global_batch=64)
    d = {"dp": 8, "pp": 1, "sharding": 1, "sep": 1, "mp": 1}
    one = CostModel(chip="v5p", n_slices=1).step_time(m, d)[1]["dp_raw_s"]
    two = CostModel(chip="v5p", n_slices=2).step_time(m, d)[1]["dp_raw_s"]
    assert two > one * 2, (one, two)
