"""ONNX export (reference: paddle.onnx.export / paddle2onnx op mappers).
The exported bytes are validated with the in-repo protobuf decoder and an
INDEPENDENT numpy evaluator of ONNX op semantics (ref_eval.py) — the
onnxruntime-less oracle."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.onnx import export, proto, ref_eval


def _roundtrip(model, example, rtol=1e-4, atol=1e-5):
    path = export(model, "/tmp/onnx_test_model", input_spec=[example])
    with open(path, "rb") as f:
        blob = f.read()
    parsed = proto.parse_model(blob)
    assert parsed["ir_version"] and parsed["opset"] >= 13
    g = parsed["graph"]
    in_name = g["inputs"][0][0]
    want = model(example).numpy()
    got = ref_eval.run(blob, {in_name: example.numpy()})[0]
    np.testing.assert_allclose(got, want, rtol=rtol, atol=atol)
    return parsed


def test_mlp_export_and_eval():
    paddle.seed(0)
    m = paddle.nn.Sequential(
        paddle.nn.Linear(8, 32), paddle.nn.ReLU(),
        paddle.nn.Linear(32, 16), paddle.nn.Tanh(),
        paddle.nn.Linear(16, 4), paddle.nn.Softmax())
    m.eval()
    x = paddle.to_tensor(np.random.RandomState(0).randn(5, 8).astype(np.float32))
    parsed = _roundtrip(m, x)
    ops = {n["op_type"] for n in parsed["graph"]["nodes"]}
    assert "MatMul" in ops


def test_lenet_conv_pool_export():
    paddle.seed(0)
    from paddle_tpu.vision.models import LeNet
    m = LeNet(num_classes=10)
    m.eval()
    x = paddle.to_tensor(np.random.RandomState(1).randn(2, 1, 28, 28)
                         .astype(np.float32))
    parsed = _roundtrip(m, x, rtol=1e-3, atol=1e-4)
    ops = {n["op_type"] for n in parsed["graph"]["nodes"]}
    assert "Conv" in ops and ("MaxPool" in ops or "AveragePool" in ops)


def test_batchnorm_eval_export():
    paddle.seed(0)
    m = paddle.nn.Sequential(
        paddle.nn.Conv2D(3, 8, 3, padding=1),
        paddle.nn.BatchNorm2D(8),
        paddle.nn.ReLU())
    m.train()
    # accumulate running stats, then export in eval mode
    for _ in range(2):
        m(paddle.to_tensor(np.random.RandomState(2).randn(4, 3, 8, 8)
                           .astype(np.float32)))
    m.eval()
    x = paddle.to_tensor(np.random.RandomState(3).randn(2, 3, 8, 8)
                         .astype(np.float32))
    _roundtrip(m, x, rtol=1e-3, atol=1e-4)


def test_unsupported_primitive_raises_by_name():
    class Weird(paddle.nn.Layer):
        def forward(self, x):
            return paddle.cumsum(x, axis=0)   # cumsum not in the subset

    m = Weird()
    x = paddle.to_tensor(np.ones((3, 3), np.float32))
    with pytest.raises(NotImplementedError, match="cumsum|unsupported"):
        export(m, "/tmp/onnx_weird", input_spec=[x])


def test_passthrough_output_gets_identity():
    """A graph output aliasing an input must be produced by a node
    (Identity), or checkers reject the model."""
    class Pass(paddle.nn.Layer):
        def forward(self, x):
            return x

    x = paddle.to_tensor(np.ones((2, 2), np.float32))
    path = export(Pass(), "/tmp/onnx_pass", input_spec=[x])
    blob = open(path, "rb").read()
    g = proto.parse_model(blob)["graph"]
    node_outs = {o for n in g["nodes"] for o in n["output"]}
    for name, _, _ in g["outputs"]:
        assert name in node_outs, f"output {name} not produced by any node"
    got = ref_eval.run(blob, {g["inputs"][0][0]: x.numpy()})[0]
    np.testing.assert_array_equal(got, x.numpy())


def test_conv_transpose_raises():
    m = paddle.nn.Conv2DTranspose(3, 4, 3, stride=2)
    m.eval()
    x = paddle.to_tensor(np.ones((1, 3, 8, 8), np.float32))
    # refuses at the kernel-flip ('rev') or the lhs_dilation guard —
    # either way, never a silent wrong Conv
    with pytest.raises(NotImplementedError,
                       match="lhs_dilation|Transpose|rev|unsupported"):
        export(m, "/tmp/onnx_ct", input_spec=[x])


def test_wire_format_roundtrip_primitives():
    """Encoder/decoder agree on every message type we emit."""
    arr = np.random.RandomState(0).randn(2, 3).astype(np.float32)
    name, back = proto.parse_tensor(proto.tensor_proto("w", arr))
    assert name == "w"
    np.testing.assert_array_equal(back, arr)

    nd = proto.parse_node(proto.node("Conv", ["a", "b"], ["c"],
                                     strides=[1, 2], group=1, alpha=1.5,
                                     mode="constant"))
    assert nd["op_type"] == "Conv" and nd["input"] == ["a", "b"]
    assert nd["attrs"]["strides"] == [1, 2] and nd["attrs"]["group"] == 1
    assert abs(nd["attrs"]["alpha"] - 1.5) < 1e-6
    assert nd["attrs"]["mode"] == "constant"

    vi = proto.parse_value_info(proto.value_info("x", np.float32, (2, 3)))
    assert vi == ("x", np.dtype(np.float32), [2, 3])
