"""Systematic nn.functional matrix vs torch (reference: the per-op
``test_activation_op.py`` / ``test_*_loss.py`` files of
``test/legacy_test/`` — every functional in the op schema must be
exercised by name; this file covers the tail the layer-level suites
don't hit directly)."""
import numpy as np
import pytest
import torch
import torch.nn.functional as TF

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

RNG = np.random.RandomState(5)


def t(x):
    return paddle.to_tensor(x)


def _cmp(got, want, rtol=1e-4, atol=1e-5):
    if isinstance(want, torch.Tensor):
        want = want.detach().numpy()
    np.testing.assert_allclose(np.asarray(got.numpy()), want,
                               rtol=rtol, atol=atol)


# -- activations: (name, paddle kwargs, torch fn) ---------------------------

ACTIVATIONS = [
    ("celu", {"alpha": 1.2}, lambda x: TF.celu(x, alpha=1.2)),
    ("elu", {"alpha": 0.8}, lambda x: TF.elu(x, alpha=0.8)),
    ("hardshrink", {}, TF.hardshrink),
    ("hardtanh", {}, TF.hardtanh),
    ("hardsigmoid", {}, TF.hardsigmoid),
    ("hardswish", {}, TF.hardswish),
    ("leaky_relu", {"negative_slope": 0.1},
     lambda x: TF.leaky_relu(x, 0.1)),
    ("log_sigmoid", {}, TF.logsigmoid),
    ("mish", {}, TF.mish),
    ("relu6", {}, TF.relu6),
    ("selu", {}, TF.selu),
    ("softplus", {}, TF.softplus),
    ("softshrink", {}, TF.softshrink),
    ("softsign", {}, TF.softsign),
    ("swish", {}, TF.silu),
    ("tanhshrink", {}, TF.tanhshrink),
]


@pytest.mark.parametrize("name,kw,ref", ACTIVATIONS,
                         ids=[a[0] for a in ACTIVATIONS])
def test_activation_matches_torch(name, kw, ref):
    x = RNG.randn(3, 4).astype(np.float32) * 2
    _cmp(getattr(F, name)(t(x), **kw), ref(torch.tensor(x)))


def test_prelu_glu_maxout_relu_():
    x = RNG.randn(2, 6).astype(np.float32)
    w = np.asarray([0.25], np.float32)
    _cmp(F.prelu(t(x), t(w)), TF.prelu(torch.tensor(x), torch.tensor(w)))
    _cmp(F.glu(t(x), axis=-1), TF.glu(torch.tensor(x), dim=-1))
    # maxout (phi MaxOutFunctor): output channel i = max over the
    # CONSECUTIVE input channels [i*groups, (i+1)*groups)
    xm = RNG.randn(2, 6, 4, 4).astype(np.float32)
    got = np.asarray(F.maxout(t(xm), groups=3).numpy())
    want = xm.reshape(2, 2, 3, 4, 4).max(axis=2)
    np.testing.assert_allclose(got, want, rtol=1e-6)
    # relu_ mutates in place
    xr = t(np.asarray([-1.0, 2.0], np.float32))
    F.relu_(xr)
    np.testing.assert_allclose(np.asarray(xr.numpy()), [0.0, 2.0])


def test_rrelu_gumbel_softmax_seeded():
    paddle.seed(3)
    x = RNG.randn(4, 5).astype(np.float32)
    # eval mode: rrelu is deterministic (mean slope)
    got = np.asarray(F.rrelu(t(x), lower=0.1, upper=0.3,
                             training=False).numpy())
    want = np.where(x >= 0, x, 0.2 * x)
    np.testing.assert_allclose(got, want, rtol=1e-5)
    # training mode: random slopes within [lower, upper], seeded
    paddle.seed(3)
    a = np.asarray(F.rrelu(t(x), training=True).numpy())
    paddle.seed(3)
    b = np.asarray(F.rrelu(t(x), training=True).numpy())
    np.testing.assert_array_equal(a, b)
    # gumbel_softmax: rows sum to 1; hard=True yields one-hot
    paddle.seed(4)
    g = np.asarray(F.gumbel_softmax(t(x), temperature=0.5).numpy())
    np.testing.assert_allclose(g.sum(-1), np.ones(4), rtol=1e-4)
    gh = np.asarray(F.gumbel_softmax(t(x), hard=True).numpy())
    assert ((gh == 0) | (gh == 1)).all() and gh.sum() == 4


# -- losses -----------------------------------------------------------------

def test_loss_matrix_matches_torch():
    p = np.clip(RNG.rand(4, 3).astype(np.float32), 0.05, 0.95)
    y = (RNG.rand(4, 3) > 0.5).astype(np.float32)
    _cmp(F.binary_cross_entropy(t(p), t(y)),
         TF.binary_cross_entropy(torch.tensor(p), torch.tensor(y)))
    logits = RNG.randn(4, 3).astype(np.float32)
    _cmp(F.binary_cross_entropy_with_logits(t(logits), t(y)),
         TF.binary_cross_entropy_with_logits(torch.tensor(logits),
                                             torch.tensor(y)))
    a = RNG.randn(4, 6).astype(np.float32)
    b = RNG.randn(4, 6).astype(np.float32)
    _cmp(F.mse_loss(t(a), t(b)), TF.mse_loss(torch.tensor(a),
                                             torch.tensor(b)))
    _cmp(F.l1_loss(t(a), t(b)), TF.l1_loss(torch.tensor(a),
                                           torch.tensor(b)))
    _cmp(F.smooth_l1_loss(t(a), t(b)),
         TF.smooth_l1_loss(torch.tensor(a), torch.tensor(b)))
    _cmp(F.kl_div(t(np.log(p)), t(p)),
         TF.kl_div(torch.tensor(np.log(p)), torch.tensor(p)))
    lab = RNG.randint(0, 3, (4,)).astype(np.int64)
    logp = np.log(p / p.sum(-1, keepdims=True))
    _cmp(F.nll_loss(t(logp.astype(np.float32)), t(lab)),
         TF.nll_loss(torch.tensor(logp.astype(np.float32)),
                     torch.tensor(lab)))
    yy = np.where(RNG.rand(4) > 0.5, 1.0, -1.0).astype(np.float32)
    _cmp(F.cosine_embedding_loss(t(a), t(b), t(yy)),
         TF.cosine_embedding_loss(torch.tensor(a), torch.tensor(b),
                                  torch.tensor(yy)))
    _cmp(F.hinge_embedding_loss(t(a), t(yy[:, None].repeat(6, 1))),
         TF.hinge_embedding_loss(torch.tensor(a),
                                 torch.tensor(yy[:, None].repeat(6, 1))))
    m1 = RNG.randn(4).astype(np.float32)
    m2 = RNG.randn(4).astype(np.float32)
    _cmp(F.margin_ranking_loss(t(m1), t(m2), t(yy)),
         TF.margin_ranking_loss(torch.tensor(m1), torch.tensor(m2),
                                torch.tensor(yy)))
    c = RNG.randn(4, 6).astype(np.float32)
    _cmp(F.triplet_margin_loss(t(a), t(b), t(c)),
         TF.triplet_margin_loss(torch.tensor(a), torch.tensor(b),
                                torch.tensor(c)), rtol=1e-3)
    # paddle-only surfaces
    _cmp(F.square_error_cost(t(a), t(b)), (a - b) ** 2)
    eps = 1e-4      # paddle log_loss epsilon inside both logs
    _cmp(F.log_loss(t(p[:, :1]), t(y[:, :1])),
         -(y[:, :1] * np.log(p[:, :1] + eps) +
           (1 - y[:, :1]) * np.log(1 - p[:, :1] + eps)), rtol=1e-4)
    sm = np.asarray(F.label_smooth(t(y), epsilon=0.1).numpy())
    np.testing.assert_allclose(sm, y * 0.9 + 0.1 / 3, rtol=1e-4)
    loss, sp = F.softmax_with_cross_entropy(
        t(logits), t(lab[:, None]), return_softmax=True)
    want = TF.cross_entropy(torch.tensor(logits), torch.tensor(lab),
                            reduction="none")
    np.testing.assert_allclose(np.asarray(loss.numpy()).ravel(),
                               want.numpy(), rtol=1e-4, atol=1e-5)
    # focal loss vs manual formula
    fl = np.asarray(F.sigmoid_focal_loss(
        t(logits), t(y), reduction="none").numpy())
    sig = 1 / (1 + np.exp(-logits))
    ce = -(y * np.log(sig) + (1 - y) * np.log(1 - sig))
    pt = y * sig + (1 - y) * (1 - sig)
    alpha_t = y * 0.25 + (1 - y) * 0.75
    np.testing.assert_allclose(fl, alpha_t * (1 - pt) ** 2 * ce,
                               rtol=1e-3, atol=1e-4)


# -- conv / pool / norm ------------------------------------------------------

def test_conv_family_matches_torch():
    x1 = RNG.randn(2, 3, 12).astype(np.float32)
    w1 = RNG.randn(4, 3, 3).astype(np.float32)
    _cmp(F.conv1d(t(x1), t(w1), padding=1),
         TF.conv1d(torch.tensor(x1), torch.tensor(w1), padding=1),
         rtol=1e-3, atol=1e-4)
    x2 = RNG.randn(2, 3, 8, 8).astype(np.float32)
    w2 = RNG.randn(5, 3, 3, 3).astype(np.float32)
    _cmp(F.conv2d(t(x2), t(w2), stride=2, padding=1),
         TF.conv2d(torch.tensor(x2), torch.tensor(w2), stride=2,
                   padding=1), rtol=1e-3, atol=1e-4)
    x3 = RNG.randn(1, 2, 5, 6, 6).astype(np.float32)
    w3 = RNG.randn(3, 2, 2, 2, 2).astype(np.float32)
    _cmp(F.conv3d(t(x3), t(w3)),
         TF.conv3d(torch.tensor(x3), torch.tensor(w3)),
         rtol=1e-3, atol=1e-4)


def test_pool_family_matches_torch():
    x = RNG.randn(2, 3, 12).astype(np.float32)
    _cmp(F.avg_pool1d(t(x), 3), TF.avg_pool1d(torch.tensor(x), 3))
    _cmp(F.adaptive_avg_pool1d(t(x), 4),
         TF.adaptive_avg_pool1d(torch.tensor(x), 4))
    x2 = RNG.randn(2, 3, 8, 8).astype(np.float32)
    _cmp(F.adaptive_avg_pool2d(t(x2), 2),
         TF.adaptive_avg_pool2d(torch.tensor(x2), 2))
    got = F.adaptive_max_pool2d(t(x2), 2)
    _cmp(got, TF.adaptive_max_pool2d(torch.tensor(x2), 2))
    v, idx = F.max_pool1d_with_index(t(x), 2)
    tv, ti = TF.max_pool1d(torch.tensor(x), 2, return_indices=True)
    _cmp(v, tv)
    np.testing.assert_array_equal(np.asarray(idx.numpy()), ti.numpy())
    # unpool round-trips the pooled values back to their argmax slots
    got = F.max_unpool1d(v, idx, 2)
    want = TF.max_unpool1d(tv, ti, 2)
    _cmp(got, want)
    x3 = RNG.randn(1, 2, 4, 4, 4).astype(np.float32)
    v3, i3 = TF.max_pool3d(torch.tensor(x3), 2, return_indices=True)
    pv3, pi3 = F.max_pool3d(t(x3), 2, return_mask=True)
    got3 = F.max_unpool3d(pv3, pi3, 2)
    _cmp(got3, TF.max_unpool3d(v3, i3, 2))


def test_norm_family_matches_torch():
    x = RNG.randn(3, 4, 5).astype(np.float32)
    _cmp(F.layer_norm(t(x), normalized_shape=[5]),
         TF.layer_norm(torch.tensor(x), [5]), rtol=1e-3, atol=1e-4)
    _cmp(F.normalize(t(x)), TF.normalize(torch.tensor(x)), rtol=1e-4)
    x4 = RNG.randn(2, 3, 6, 6).astype(np.float32)
    rm = np.zeros(3, np.float32)
    rv = np.ones(3, np.float32)
    _cmp(F.batch_norm(t(x4), t(rm), t(rv), training=False),
         TF.batch_norm(torch.tensor(x4), torch.tensor(rm),
                       torch.tensor(rv)), rtol=1e-3, atol=1e-4)
    _cmp(F.instance_norm(t(x4)), TF.instance_norm(torch.tensor(x4)),
         rtol=1e-3, atol=1e-4)
    _cmp(F.local_response_norm(t(x4), size=3),
         TF.local_response_norm(torch.tensor(x4), 3), rtol=1e-3,
         atol=1e-4)
    # rms_norm vs manual formula
    w = RNG.rand(5).astype(np.float32) + 0.5
    got = np.asarray(F.rms_norm(t(x), t(w), epsilon=1e-6).numpy())
    ref = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6) * w
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_misc_functionals():
    x = RNG.randn(2, 4, 6).astype(np.float32)
    w = RNG.randn(6, 3).astype(np.float32)
    b = RNG.randn(3).astype(np.float32)
    _cmp(F.linear(t(x), t(w), t(b)),
         torch.tensor(x) @ torch.tensor(w) + torch.tensor(b),
         rtol=1e-4, atol=1e-4)
    a = RNG.randn(3, 8).astype(np.float32)
    c = RNG.randn(3, 8).astype(np.float32)
    _cmp(F.cosine_similarity(t(a), t(c)),
         TF.cosine_similarity(torch.tensor(a), torch.tensor(c)),
         rtol=1e-4)
    x4 = RNG.randn(1, 4, 3, 3).astype(np.float32)
    _cmp(F.pixel_shuffle(t(x4), 2),
         TF.pixel_shuffle(torch.tensor(x4), 2))
    up = RNG.randn(1, 2, 4, 4).astype(np.float32)
    _cmp(F.upsample(t(up), scale_factor=2),
         TF.interpolate(torch.tensor(up), scale_factor=2), rtol=1e-4)
    # unfold_channels: paddle's F.unfold (im2col)
    ix = RNG.randn(1, 2, 5, 5).astype(np.float32)
    _cmp(F.unfold_channels(t(ix), 3) if hasattr(F, "unfold_channels")
         else F.unfold(t(ix), 3),
         TF.unfold(torch.tensor(ix), 3), rtol=1e-4)
    got = np.asarray(F.unfold_channels(t(ix), 3).numpy())
    np.testing.assert_allclose(got, TF.unfold(torch.tensor(ix), 3).numpy(),
                               rtol=1e-4, atol=1e-5)


def test_dropout_variants_train_eval():
    x = np.ones((4, 3, 6, 6), np.float32)
    paddle.seed(0)
    for fn, arg in ((F.dropout2d, t(x)), (F.dropout3d, t(x[..., None])),
                    (F.alpha_dropout, t(x))):
        out_eval = np.asarray(fn(arg, training=False).numpy())
        np.testing.assert_allclose(out_eval, np.asarray(arg.numpy()))
        out_train = np.asarray(fn(arg, p=0.5, training=True).numpy())
        assert out_train.shape == np.asarray(arg.numpy()).shape
        assert not np.allclose(out_train, np.asarray(arg.numpy()))


def test_round4_static_and_incubate_api():
    """static scope/py_func/places + incubate graph_send_recv /
    softmax_mask_fuse round-4 parity additions."""
    import numpy as np
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.static as static
    import paddle_tpu.incubate as incubate

    # scope + guard
    s = static.Scope()
    with static.scope_guard(s):
        assert static.global_scope() is s
        s.var("w").set(np.ones(3))
        assert (s.find_var("w").get_tensor() == 1).all()
    assert static.global_scope() is not s
    assert len(static.cpu_places(2)) == 2

    # py_func through jit (pure_callback keeps it compiled)
    x = paddle.to_tensor(np.arange(4, dtype="float32"))
    out_t = static.InputSpec([4], "float32")

    def host_fn(t):
        return paddle.to_tensor(t.numpy() * 3.0)

    y = static.py_func(host_fn, x, out_t)
    np.testing.assert_allclose(y.numpy(), np.arange(4) * 3.0)

    @paddle.jit.to_static
    def traced(a):
        return static.py_func(host_fn, a, out_t) + 1.0

    np.testing.assert_allclose(traced(x).numpy(), np.arange(4) * 3 + 1)

    # incubate shims
    xg = paddle.to_tensor(np.eye(3, dtype="float32"))
    src = paddle.to_tensor(np.array([0, 1, 2], "int32"))
    dst = paddle.to_tensor(np.array([1, 1, 0], "int32"))
    agg = incubate.graph_send_recv(xg, src, dst, pool_type="sum")
    np.testing.assert_allclose(agg.numpy()[1], [1.0, 1.0, 0.0])

    logits = paddle.to_tensor(np.zeros((1, 1, 2, 2), "float32"))
    m = paddle.to_tensor(np.array([[[[0.0, -1e30], [0.0, 0.0]]]], "float32"))
    sm = incubate.softmax_mask_fuse(logits, m)
    np.testing.assert_allclose(sm.numpy()[0, 0, 0], [1.0, 0.0], atol=1e-6)
    tri = incubate.softmax_mask_fuse_upper_triangle(logits)
    np.testing.assert_allclose(tri.numpy()[0, 0, 0], [1.0, 0.0], atol=1e-6)
    import paddle_tpu.amp as amp
    assert amp.is_bfloat16_supported() is True
    assert amp.is_float16_supported("cpu") is False
    assert amp.is_float16_supported("gpu:0") is True

    # py_func with grad-enabled inputs: opaque (zero grad) without a
    # backward_func, custom host backward with one
    xa = paddle.to_tensor(np.arange(4, dtype="float32"))
    xa.stop_gradient = False
    y0 = static.py_func(host_fn, xa, out_t)
    y0.sum().backward()   # must not raise; grads are zero
    np.testing.assert_allclose(xa.grad.numpy(), np.zeros(4))

    # reference contract: backward_func(inputs, OUTPUTS, out_grads)
    def host_bwd(inp, out, g):
        return paddle.to_tensor(g.numpy() * 3.0 + 0.0 * out.numpy())

    xb = paddle.to_tensor(np.arange(4, dtype="float32"))
    xb.stop_gradient = False
    y1 = static.py_func(host_fn, xb, out_t, backward_func=host_bwd)
    y1.sum().backward()
    np.testing.assert_allclose(xb.grad.numpy(), np.full(4, 3.0))

    # skip_vars_in_backward_input drops the named var from the callback
    # args (here: the forward output — backward sees (input, grad) only)
    def host_bwd_skip(inp, g):
        return paddle.to_tensor(g.numpy() * inp.numpy())

    xc = paddle.to_tensor(np.arange(4, dtype="float32"))
    xc.stop_gradient = False
    yc = static.py_func(host_fn, xc, out_t, backward_func=host_bwd_skip,
                        skip_vars_in_backward_input=[out_t])
    yc.sum().backward()
    np.testing.assert_allclose(xc.grad.numpy(),
                               np.arange(4, dtype="float32"))
