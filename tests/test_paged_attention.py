"""Paged-attention serving tier (VERDICT.md round-1 item 10; reference:
``block_multihead_attention`` / ``fused_multi_transformer``'s paged KV
serving path). Kernel runs in interpret mode on CPU; the same code path
Mosaic-compiles on TPU."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.ops.pallas.paged_attention import (paged_attention,
                                                  paged_attention_reference)
from paddle_tpu.models.generation import KVCache, PagedKVCache


def _setup(batch=3, heads=8, kv_heads=4, d=64, page_size=8, pages_per_seq=4,
           lens=(5, 17, 32), seed=0):
    rng = np.random.RandomState(seed)
    n_pages = batch * pages_per_seq
    q = jnp.asarray(rng.randn(batch, heads, d), jnp.float32)
    kp = jnp.asarray(rng.randn(kv_heads, n_pages, page_size, d), jnp.float32)
    vp = jnp.asarray(rng.randn(kv_heads, n_pages, page_size, d), jnp.float32)
    tables = (np.arange(batch)[:, None] * pages_per_seq
              + np.arange(pages_per_seq)[None, :]).astype(np.int32)
    ctx = np.asarray(lens, np.int32)
    return q, kp, vp, jnp.asarray(tables), jnp.asarray(ctx)


def test_kernel_matches_reference_ragged_gqa():
    q, kp, vp, tables, ctx = _setup()
    out = paged_attention(q, kp, vp, tables, ctx, interpret=True)
    ref = paged_attention_reference(q, kp, vp, tables, ctx)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_kernel_nonuniform_block_table():
    """Pages deliberately permuted/shared — the block table, not layout,
    defines the sequence."""
    q, kp, vp, _, _ = _setup(batch=2, pages_per_seq=3, lens=(20, 9))
    tables = jnp.asarray(np.array([[5, 0, 3], [2, 4, 0]], np.int32))
    ctx = jnp.asarray(np.array([20, 9], np.int32))
    out = paged_attention(q[:2], kp, vp, tables, ctx, interpret=True)
    ref = paged_attention_reference(q[:2], kp, vp, tables, ctx)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("family", ["llama", "gpt"])
def test_paged_generate_matches_dense(family):
    """Greedy decode parity: paged cache == concat cache == no cache."""
    paddle.seed(0)
    if family == "llama":
        from paddle_tpu.models import LlamaForCausalLM, llama_tiny
        model = LlamaForCausalLM(llama_tiny(num_hidden_layers=2))
        vocab = model.config.vocab_size
    else:
        from paddle_tpu.models import GPTForCausalLM, gpt_tiny
        cfg = gpt_tiny()
        model = GPTForCausalLM(cfg)
        vocab = cfg.vocab_size
    rng = np.random.RandomState(1)
    ids = paddle.to_tensor(rng.randint(0, vocab, (2, 7)).astype(np.int64))

    dense = model.generate(ids, max_new_tokens=6)
    paged = model.generate(ids, max_new_tokens=6, use_paged_cache=True,
                           page_size=4)
    np.testing.assert_array_equal(np.asarray(dense._data),
                                  np.asarray(paged._data))


def test_paged_cache_prefill_then_steps():
    """Cache state evolves correctly across prefill + multiple decodes."""
    paddle.seed(0)
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    model = LlamaForCausalLM(llama_tiny(num_hidden_layers=2))
    model.eval()
    rng = np.random.RandomState(2)
    ids = paddle.to_tensor(rng.randint(0, 128, (2, 5)).astype(np.int64))

    from paddle_tpu.autograd.tape import no_grad
    with no_grad():
        dense_c, paged_c = KVCache(), PagedKVCache(page_size=4, max_len=16)
        ld = model(ids, cache=dense_c)
        lp = model(ids, cache=paged_c)
        np.testing.assert_allclose(np.asarray(ld._data), np.asarray(lp._data),
                                   rtol=2e-4, atol=2e-4)
        nxt = paddle.to_tensor(np.argmax(np.asarray(ld._data)[:, -1], -1)
                               .astype(np.int64)[:, None])
        for _ in range(3):
            ld = model(nxt, cache=dense_c)
            lp = model(nxt, cache=paged_c)
            np.testing.assert_allclose(np.asarray(ld._data),
                                       np.asarray(lp._data),
                                       rtol=2e-4, atol=2e-4)
            nxt = paddle.to_tensor(np.argmax(np.asarray(ld._data)[:, -1], -1)
                                   .astype(np.int64)[:, None])


def test_paged_chunked_prefill_sees_prior_context():
    """Second multi-token chunk into a warm cache must attend over the
    cached prefix (parity vs the concat cache)."""
    paddle.seed(0)
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    model = LlamaForCausalLM(llama_tiny(num_hidden_layers=2))
    model.eval()
    rng = np.random.RandomState(3)
    c1 = paddle.to_tensor(rng.randint(0, 128, (2, 6)).astype(np.int64))
    c2 = paddle.to_tensor(rng.randint(0, 128, (2, 5)).astype(np.int64))

    from paddle_tpu.autograd.tape import no_grad
    with no_grad():
        dense_c, paged_c = KVCache(), PagedKVCache(page_size=4, max_len=16)
        model(c1, cache=dense_c)
        model(c1, cache=paged_c)
        ld = model(c2, cache=dense_c)
        lp = model(c2, cache=paged_c)
    np.testing.assert_allclose(np.asarray(ld._data), np.asarray(lp._data),
                               rtol=2e-4, atol=2e-4)


def test_paged_cache_overflow_raises():
    c = PagedKVCache(page_size=4, max_len=8)
    q = paddle.to_tensor(np.zeros((1, 9, 2, 8), np.float32))
    with pytest.raises(ValueError, match="overflow"):
        c.attend(object(), q, q, q)


def test_xla_decode_tier_matches_reference():
    """The pure-XLA decode tier (PADDLE_TPU_PAGED_IMPL=xla, used when the
    session must avoid all Mosaic compiles) vs the dense oracle — jitted,
    ragged context lengths, GQA."""
    import math

    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas.paged_attention import (
        _paged_attention_xla, paged_attention_reference)

    rng = np.random.default_rng(0)
    kvh, npages, ps, d = 4, 12, 8, 32
    b, h = 3, 8
    q = jnp.asarray(rng.standard_normal((b, h, d)), jnp.float32)
    kp = jnp.asarray(rng.standard_normal((kvh, npages, ps, d)), jnp.float32)
    vp = jnp.asarray(rng.standard_normal((kvh, npages, ps, d)), jnp.float32)
    tbl = jnp.asarray(rng.integers(0, npages, (b, 4)), jnp.int32)
    lens = jnp.asarray([5, 17, 32], jnp.int32)
    out = jax.jit(lambda *a: _paged_attention_xla(
        *a, sm_scale=1 / math.sqrt(d)))(q, kp, vp, tbl, lens)
    ref = paged_attention_reference(q, kp, vp, tbl, lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
