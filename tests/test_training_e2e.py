"""M0 exit: end-to-end dygraph training (ResNet on synthetic CIFAR-shaped
data), checkpoints, hapi Model — SURVEY.md §7.1 M0."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer, io
from paddle_tpu.vision import datasets, models


def test_resnet18_overfits_small_batch():
    paddle.seed(0)
    net = models.resnet18(num_classes=4)
    opt = optimizer.Momentum(learning_rate=0.05, momentum=0.9,
                             parameters=net.parameters())
    loss_fn = nn.CrossEntropyLoss()
    x = paddle.randn([8, 3, 32, 32])
    y = paddle.to_tensor(np.array([0, 1, 2, 3] * 2))
    net.train()
    losses = []
    for _ in range(8):
        loss = loss_fn(net(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses


def test_dataloader_training_loop_mlp():
    paddle.seed(1)
    ds = datasets.FakeData(size=64, image_shape=(3, 8, 8), num_classes=3)
    dl = io.DataLoader(ds, batch_size=16, shuffle=True)
    net = nn.Sequential(nn.Flatten(), nn.Linear(192, 32), nn.ReLU(),
                        nn.Linear(32, 3))
    opt = optimizer.Adam(learning_rate=1e-2, parameters=net.parameters())
    loss_fn = nn.CrossEntropyLoss()
    first = last = None
    for epoch in range(4):
        for x, y in dl:
            loss = loss_fn(net(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            if first is None:
                first = float(loss)
            last = float(loss)
    assert last < first


def test_save_load_roundtrip(tmp_path):
    net = models.LeNet()
    opt = optimizer.AdamW(learning_rate=1e-3, parameters=net.parameters())
    x = paddle.randn([2, 1, 28, 28])
    nn.functional.cross_entropy(net(x), paddle.to_tensor([1, 2])).backward()
    opt.step()
    opt.clear_grad()
    p = str(tmp_path / "ckpt")
    paddle.save(net.state_dict(), p + ".pdparams")
    paddle.save(opt.state_dict(), p + ".pdopt")

    net2 = models.LeNet()
    net2.set_state_dict(paddle.load(p + ".pdparams"))
    for (n1, p1), (n2, p2) in zip(net.named_parameters(), net2.named_parameters()):
        np.testing.assert_allclose(p1.numpy(), p2.numpy())
    opt2 = optimizer.AdamW(learning_rate=1e-3, parameters=net2.parameters())
    opt2.set_state_dict(paddle.load(p + ".pdopt"))
    out1 = net(x).numpy()
    out2 = net2(x).numpy()
    np.testing.assert_allclose(out1, out2, rtol=1e-5)


def test_save_load_nested_object(tmp_path):
    obj = {"a": paddle.ones([2]), "b": [paddle.zeros([1]), 3], "c": "str"}
    p = str(tmp_path / "obj.pkl")
    paddle.save(obj, p)
    loaded = paddle.load(p)
    np.testing.assert_allclose(loaded["a"].numpy(), [1, 1])
    assert loaded["b"][1] == 3 and loaded["c"] == "str"


def test_hapi_model_fit_eval():
    paddle.seed(2)
    ds = datasets.FakeData(size=32, image_shape=(1, 12, 12), num_classes=2)
    net = nn.Sequential(nn.Flatten(), nn.Linear(144, 2))
    model = paddle.Model(net)
    from paddle_tpu.metric import Accuracy
    model.prepare(optimizer.Adam(learning_rate=0.01,
                                 parameters=net.parameters()),
                  nn.CrossEntropyLoss(), Accuracy())
    model.fit(ds, batch_size=8, epochs=1, verbose=0)
    res = model.evaluate(ds, batch_size=8, verbose=0)
    assert "acc" in res and "loss" in res


def test_amp_autocast_and_scaler():
    paddle.seed(3)
    net = nn.Linear(8, 8)
    opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=128.0)
    x = paddle.randn([4, 8])
    with paddle.amp.auto_cast(level="O1"):
        out = net(x)
        # matmul ran in fp16 under O1
        assert str(np.dtype(out.dtype)) == "float16"
        loss = out.astype("float32").mean()
    scaled = scaler.scale(loss)
    scaled.backward()
    w_before = net.weight.numpy().copy()
    scaler.step(opt)
    opt.clear_grad()
    assert not np.allclose(net.weight.numpy(), w_before)


def test_amp_scaler_skips_on_inf():
    net = nn.Linear(2, 2)
    opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=4.0)
    net.weight.grad = paddle.to_tensor(np.full((2, 2), np.inf, np.float32))
    net.bias.grad = paddle.zeros([2])
    w0 = net.weight.numpy().copy()
    scaler.step(opt)
    np.testing.assert_allclose(net.weight.numpy(), w0)  # step skipped
    assert scaler.get_scale_ratio() == 2.0  # halved


def test_nan_inf_flag():
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    try:
        x = paddle.to_tensor([1.0, 0.0])
        with pytest.raises(FloatingPointError):
            _ = paddle.log(x - 1.0)  # log(-1) -> nan
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False})
