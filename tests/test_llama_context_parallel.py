"""Llama with context parallelism (ring attention over 'sep') — parity vs
the plain model under jit (SURVEY.md §5.7)."""
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.models import LlamaForCausalLM, llama_tiny
from paddle_tpu.framework.functional import FunctionalModule


def test_llama_cp_matches_plain():
    paddle.seed(0)
    model = LlamaForCausalLM(llama_tiny(max_position_embeddings=128))
    model.eval()
    fm = FunctionalModule(model, training=False)
    p = fm.param_arrays()
    key = fm.next_key()
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 128, (2, 64)),
                      jnp.int32)

    ref = jax.jit(lambda p, i: fm(p, [], key, i)[0])(p, ids)

    mesh = mesh_mod.init_mesh({"dp": 2, "sep": 4})
    try:
        model.config.context_parallel = True
        ids_sh = jax.device_put(ids, NamedSharding(mesh, P("dp", "sep")))
        out = jax.jit(lambda p, i: fm(p, [], key, i)[0])(p, ids_sh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)
    finally:
        model.config.context_parallel = False
        mesh_mod.reset_mesh()


def test_llama_cp_trains():
    paddle.seed(1)
    mesh = mesh_mod.init_mesh({"sep": 4, "dp": 2})
    try:
        model = LlamaForCausalLM(llama_tiny(max_position_embeddings=128,
                                            context_parallel=True))
        fm = FunctionalModule(model, training=True)
        p = fm.param_arrays()
        key = fm.next_key()
        rng = np.random.default_rng(1)
        ids = jnp.asarray(rng.integers(0, 128, (2, 32)), jnp.int32)
        labels = jnp.asarray(rng.integers(0, 128, (2, 32)), jnp.int32)

        @jax.jit
        def step(p):
            def loss_fn(p):
                (loss, _), _ = fm(p, [], key, ids, labels=labels)
                return loss
            loss, g = jax.value_and_grad(loss_fn)(p)
            return loss, [a - 1e-2 * ga for a, ga in zip(p, g)]

        losses = []
        for _ in range(3):
            loss, p = step(p)
            losses.append(float(loss))
        assert losses[-1] < losses[0]
        assert np.isfinite(losses).all()
    finally:
        mesh_mod.reset_mesh()
