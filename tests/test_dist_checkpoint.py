"""Distributed checkpoint tests: sharded save, reshard-on-load, async save,
group-sharded gather (SURVEY.md §5.4)."""
import os

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed import checkpoint as ckpt
from paddle_tpu.distributed import mesh as mesh_mod


def test_save_load_roundtrip_plain(tmp_path):
    path = str(tmp_path / "ck")
    sd = {"w": paddle.randn([4, 8]), "b": paddle.randn([8]),
          "opt": {"step": 7, "m": paddle.randn([4, 8])}}
    ref_w = sd["w"].numpy().copy()
    ref_m = sd["opt"]["m"].numpy().copy()
    ckpt.save_state_dict(sd, path)
    assert os.path.exists(os.path.join(path, "metadata.json"))

    tgt = {"w": paddle.zeros([4, 8]), "b": paddle.zeros([8]),
           "opt": {"step": 0, "m": paddle.zeros([4, 8])}}
    ckpt.load_state_dict(tgt, path)
    np.testing.assert_allclose(tgt["w"].numpy(), ref_w)
    np.testing.assert_allclose(tgt["opt"]["m"].numpy(), ref_m)
    assert tgt["opt"]["step"] == 7


def test_sharded_save_and_reshard_on_load(tmp_path):
    path = str(tmp_path / "ck")
    mesh = mesh_mod.init_mesh({"dp": 2, "mp": 4})
    try:
        val = np.arange(64, dtype=np.float32).reshape(8, 8)
        arr = jax.device_put(jnp.asarray(val),
                             NamedSharding(mesh, P("mp", None)))
        t = paddle.to_tensor(arr)
        ckpt.save_state_dict({"w": t}, path)
        # multiple shard files written
        files = [f for f in os.listdir(path) if f.endswith(".npy")]
        assert len(files) == 4, files

        # reshard-on-load onto a DIFFERENT layout (dp-sharded dim 1)
        tgt_arr = jax.device_put(jnp.zeros((8, 8), jnp.float32),
                                 NamedSharding(mesh, P(None, "dp")))
        tgt = {"w": paddle.to_tensor(tgt_arr)}
        ckpt.load_state_dict(tgt, path)
        np.testing.assert_allclose(np.asarray(tgt["w"]._data), val)
        assert tgt["w"]._data.sharding.spec == P(None, "dp")
    finally:
        mesh_mod.reset_mesh()


def test_async_save(tmp_path):
    path = str(tmp_path / "ck")
    sd = {"w": paddle.randn([16, 16])}
    ref = sd["w"].numpy().copy()
    h = ckpt.save_state_dict(sd, path, async_save=True)
    h.wait()
    tgt = {"w": paddle.zeros([16, 16])}
    ckpt.load_state_dict(tgt, path)
    np.testing.assert_allclose(tgt["w"].numpy(), ref)


def test_save_group_sharded_model(tmp_path):
    out = str(tmp_path / "gs")
    model = paddle.nn.Linear(4, 4)
    opt = paddle.optimizer.AdamW(parameters=model.parameters())
    # one step so optimizer has state
    loss = model(paddle.randn([2, 4])).sum()
    loss.backward()
    opt.step()
    ckpt.save_group_sharded_model(model, out, optimizer=opt)
    assert os.path.exists(os.path.join(out, "model.pdparams"))
    assert os.path.exists(os.path.join(out, "model.pdopt"))
    sd = paddle.load(os.path.join(out, "model.pdparams"))
    np.testing.assert_allclose(sd["weight"].numpy(), model.weight.numpy())


def test_shard_filenames_are_slice_derived(tmp_path):
    """Round-2 ADVICE high fix: filenames must encode the global slice so
    different hosts can never collide on a per-process counter."""
    path = str(tmp_path / "ck")
    mesh = mesh_mod.init_mesh({"dp": 2, "mp": 4})
    try:
        val = np.arange(64, dtype=np.float32).reshape(8, 8)
        arr = jax.device_put(jnp.asarray(val),
                             NamedSharding(mesh, P("mp", None)))
        ckpt.save_state_dict({"w": paddle.to_tensor(arr)}, path)
        files = sorted(f for f in os.listdir(path) if f.endswith(".npy"))
        # slice-span names, one per distinct slice — no shard0/shard1 counters
        assert files == ["w.s0-2_0-8.npy", "w.s2-4_0-8.npy",
                         "w.s4-6_0-8.npy", "w.s6-8_0-8.npy"], files
        # per-rank metadata exists alongside the merged global one
        assert os.path.exists(os.path.join(path, "metadata.rank0.json"))
        assert os.path.exists(os.path.join(path, "metadata.json"))
    finally:
        mesh_mod.reset_mesh()


def test_multihost_metadata_merge(tmp_path):
    """Simulate a second host: its rank metadata + shard files must appear
    in the merged metadata.json and be readable at load (previously the
    coordinator wrote only its own addressable shards and _assemble
    zero-filled the rest)."""
    import json
    path = str(tmp_path / "ck")
    os.makedirs(path)
    full = np.arange(16, dtype=np.float32).reshape(4, 4)
    # rank 0 owns rows 0:2, rank 1 rows 2:4 — write both sides by hand
    np.save(os.path.join(path, "w.s0-2_0-4.npy"), full[:2])
    np.save(os.path.join(path, "w.s2-4_0-4.npy"), full[2:])
    meta0 = {"version": 1, "nonarray": {"step": 3}, "tensors": {
        "w": {"shape": [4, 4], "dtype": "float32", "shards": [
            {"file": "w.s0-2_0-4.npy", "index": [[0, 2], [0, 4]]}]}}}
    meta1 = {"version": 1, "nonarray": {}, "tensors": {
        "w": {"shape": [4, 4], "dtype": "float32", "shards": [
            {"file": "w.s2-4_0-4.npy", "index": [[2, 4], [0, 4]]}]}}}
    for r, m in ((0, meta0), (1, meta1)):
        with open(os.path.join(path, f"metadata.rank{r}.json"), "w") as f:
            json.dump(m, f)
    merged = ckpt._merge_rank_meta(path, nprocs=2, timeout=5)
    assert len(merged["tensors"]["w"]["shards"]) == 2
    with open(os.path.join(path, "metadata.json"), "w") as f:
        json.dump(merged, f)

    tgt = {"w": paddle.zeros([4, 4]), "step": 0}
    ckpt.load_state_dict(tgt, path)
    np.testing.assert_allclose(tgt["w"].numpy(), full)
