"""Distributed checkpoint tests: sharded save, reshard-on-load, async save,
group-sharded gather (SURVEY.md §5.4)."""
import os

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed import checkpoint as ckpt
from paddle_tpu.distributed import mesh as mesh_mod


def test_save_load_roundtrip_plain(tmp_path):
    path = str(tmp_path / "ck")
    sd = {"w": paddle.randn([4, 8]), "b": paddle.randn([8]),
          "opt": {"step": 7, "m": paddle.randn([4, 8])}}
    ref_w = sd["w"].numpy().copy()
    ref_m = sd["opt"]["m"].numpy().copy()
    ckpt.save_state_dict(sd, path)
    assert os.path.exists(os.path.join(path, "metadata.json"))

    tgt = {"w": paddle.zeros([4, 8]), "b": paddle.zeros([8]),
           "opt": {"step": 0, "m": paddle.zeros([4, 8])}}
    ckpt.load_state_dict(tgt, path)
    np.testing.assert_allclose(tgt["w"].numpy(), ref_w)
    np.testing.assert_allclose(tgt["opt"]["m"].numpy(), ref_m)
    assert tgt["opt"]["step"] == 7


def test_sharded_save_and_reshard_on_load(tmp_path):
    path = str(tmp_path / "ck")
    mesh = mesh_mod.init_mesh({"dp": 2, "mp": 4})
    try:
        val = np.arange(64, dtype=np.float32).reshape(8, 8)
        arr = jax.device_put(jnp.asarray(val),
                             NamedSharding(mesh, P("mp", None)))
        t = paddle.to_tensor(arr)
        ckpt.save_state_dict({"w": t}, path)
        # multiple shard files written
        files = [f for f in os.listdir(path) if f.endswith(".npy")]
        assert len(files) == 4, files

        # reshard-on-load onto a DIFFERENT layout (dp-sharded dim 1)
        tgt_arr = jax.device_put(jnp.zeros((8, 8), jnp.float32),
                                 NamedSharding(mesh, P(None, "dp")))
        tgt = {"w": paddle.to_tensor(tgt_arr)}
        ckpt.load_state_dict(tgt, path)
        np.testing.assert_allclose(np.asarray(tgt["w"]._data), val)
        assert tgt["w"]._data.sharding.spec == P(None, "dp")
    finally:
        mesh_mod.reset_mesh()


def test_async_save(tmp_path):
    path = str(tmp_path / "ck")
    sd = {"w": paddle.randn([16, 16])}
    ref = sd["w"].numpy().copy()
    h = ckpt.save_state_dict(sd, path, async_save=True)
    h.wait()
    tgt = {"w": paddle.zeros([16, 16])}
    ckpt.load_state_dict(tgt, path)
    np.testing.assert_allclose(tgt["w"].numpy(), ref)


def test_save_group_sharded_model(tmp_path):
    out = str(tmp_path / "gs")
    model = paddle.nn.Linear(4, 4)
    opt = paddle.optimizer.AdamW(parameters=model.parameters())
    # one step so optimizer has state
    loss = model(paddle.randn([2, 4])).sum()
    loss.backward()
    opt.step()
    ckpt.save_group_sharded_model(model, out, optimizer=opt)
    assert os.path.exists(os.path.join(out, "model.pdparams"))
    assert os.path.exists(os.path.join(out, "model.pdopt"))
    sd = paddle.load(os.path.join(out, "model.pdparams"))
    np.testing.assert_allclose(sd["weight"].numpy(), model.weight.numpy())
