"""Distributed pass registry tests (SURVEY.md §2.3 'Distributed passes')."""
import pytest

from paddle_tpu.distributed.passes import (
    new_pass, PassManager, PassBase, register_pass,
)


def test_registry_and_manager():
    pm = PassManager([
        new_pass("auto_parallel_amp", {"level": "O2"}),
        new_pass("auto_parallel_recompute", {"granularity": "full"}),
        new_pass("auto_parallel_sharding", {"stage": 3}),
        new_pass("pipeline_scheduler", {"schedule_mode": "1F1B",
                                        "accumulate_steps": 8}),
        new_pass("fuse_all_reduce"),
    ])
    assert "auto_parallel_amp" in pm.names
    plan = pm.apply({})
    assert plan["amp"]["dtype"] == "bfloat16"
    assert plan["amp"]["master_weights"]
    assert plan["recompute"]["enable"]
    assert plan["sharding"]["stage"] == 3
    assert plan["pipeline"]["accumulate_steps"] == 8
    assert any("XLA" in n for n in plan["notes"])


def test_unknown_pass_and_bad_schedule():
    with pytest.raises(ValueError, match="unknown pass"):
        new_pass("nope")
    p = new_pass("pipeline_scheduler", {"schedule_mode": "bogus"})
    with pytest.raises(ValueError, match="unknown pipeline schedule"):
        p.check({})


def test_custom_pass_registration():
    @register_pass("my_test_pass")
    class MyPass(PassBase):
        def apply(self, plan, *a, **kw):
            plan["custom"] = True
            return plan

    plan = PassManager([new_pass("my_test_pass")]).apply({})
    assert plan["custom"]


def test_gradient_merge_real_semantics():
    """GradientMergePass.wrap: the optimizer applies every k-th step with
    averaged accumulated grads — parity vs one big-batch step."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.distributed.passes import new_pass

    def make():
        paddle.seed(5)
        m = paddle.nn.Linear(4, 3)
        return m, paddle.optimizer.SGD(learning_rate=0.1,
                                       parameters=m.parameters())

    rng = np.random.RandomState(0)
    xs = [rng.randn(8, 4).astype(np.float32) for _ in range(2)]

    # oracle: one step on the concatenated batch
    m1, o1 = make()
    loss = (m1(paddle.to_tensor(np.concatenate(xs))) ** paddle.to_tensor(2.0)).mean()
    loss.backward()
    o1.step()
    w_oracle = np.asarray(m1.weight._data)

    # gradient merge: two half-batches, k_steps=2
    m2, o2 = make()
    gm = new_pass("auto_parallel_gradient_merge", {"k_steps": 2, "avg": True})
    opt = gm.wrap(o2)
    for x in xs:
        loss = (m2(paddle.to_tensor(x)) ** paddle.to_tensor(2.0)).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    np.testing.assert_allclose(np.asarray(m2.weight._data), w_oracle,
                               rtol=1e-5, atol=1e-6)


def test_new_round2_passes_registered():
    from paddle_tpu.distributed.passes import new_pass, PassManager

    pm = PassManager([new_pass("auto_parallel_master_grad"),
                      new_pass("fuse_gemm_epilogue"),
                      new_pass("allreduce_matmul_grad_overlapping")])
    plan = pm.apply({})
    assert plan["amp"]["master_grad"] is True
    assert len(plan["notes"]) == 2


def test_plan_executes_into_strategy_and_training():
    """The pass plan is EXECUTED, not just recorded: build a strategy from
    it, push model-config knobs, and run a hybrid step with those degrees
    (closes the plan -> strategy -> running-step loop)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.distributed.passes import (
        new_pass, PassManager, build_strategy_from_plan,
        apply_plan_to_config,
    )
    from paddle_tpu.models import llama_tiny

    pm = PassManager([
        new_pass("auto_parallel_amp", {"level": "O2"}),
        new_pass("auto_parallel_recompute", {"granularity": "full"}),
        new_pass("auto_parallel_sharding", {"stage": 2, "degree": 2}),
        new_pass("pipeline_scheduler", {"schedule_mode": "1F1B",
                                        "accumulate_steps": 2}),
    ])
    plan = pm.apply({})
    strat = build_strategy_from_plan(plan)
    assert strat.amp and strat.amp_configs["dtype"] == "bfloat16"
    assert strat.recompute and strat.recompute_configs["granularity"] \
        == "full"
    assert strat.sharding and strat.hybrid_configs["sharding_degree"] == 2
    # the knobs land where the RUNTIME reads them
    assert strat.hybrid_configs["sharding_configs"]["stage"] == 2
    assert strat.hybrid_configs["pp_configs"]["accumulate_steps"] == 2
    assert strat.hybrid_configs["pp_configs"]["schedule_mode"] == "1F1B"

    cfg = llama_tiny()
    assert not cfg.use_recompute
    apply_plan_to_config(plan, cfg)
    assert cfg.use_recompute and cfg.recompute_granularity == "full"

    # the strategy actually drives a training step: fleet hybrid with the
    # plan's sharding degree PRESERVED (merge dp in through the full dict
    # so the defaults-merging setter can't drop plan values)
    from paddle_tpu.distributed import fleet
    from paddle_tpu import nn, optimizer as opt
    h = dict(strat.hybrid_configs)
    h["dp_degree"] = 4
    strat.hybrid_configs = h
    assert strat.hybrid_configs["sharding_degree"] == 2
    assert strat.hybrid_configs["sharding_configs"]["stage"] == 2
    fleet.init(is_collective=True, strategy=strat)
    try:
        paddle.seed(0)
        model = nn.Linear(8, 8)
        model = fleet.distributed_model(model)
        o = fleet.distributed_optimizer(
            opt.AdamW(learning_rate=1e-3, parameters=model.parameters()))
        x = paddle.to_tensor(np.random.RandomState(0)
                             .rand(8, 8).astype("float32"))
        loss = (model(x) ** 2).mean()
        loss.backward()
        o.step()
        o.clear_grad()
        assert np.isfinite(float(loss.numpy()))
    finally:
        from paddle_tpu.distributed import mesh as mesh_mod
        mesh_mod.reset_mesh()


def test_xla_builtin_passes_pin_flags_and_install():
    """The XLA-builtin passes are no longer note-only: applying them pins
    concrete compiler flags, and install_xla_flags arms them (TPU only —
    other backends would reject unknown flags)."""
    from paddle_tpu.distributed.passes import install_xla_flags, new_pass

    plan = {}
    new_pass("fuse_all_reduce").apply(plan)
    new_pass("allreduce_matmul_grad_overlapping").apply(plan)
    assert any("async_collective_fusion" in f for f in plan["xla_flags"])
    assert any("latency_hiding_scheduler" in f for f in plan["xla_flags"])

    env = {"XLA_FLAGS": "--existing=1"}
    added = install_xla_flags(plan, env=env, platform="tpu")
    assert added and all(a in env["XLA_FLAGS"] for a in added)
    assert env["XLA_FLAGS"].startswith("--existing=1")
    # idempotent: a second install adds nothing
    assert install_xla_flags(plan, env=env, platform="tpu") == []
    # non-TPU backends: never touched
    env2 = {}
    assert install_xla_flags(plan, env=env2, platform="cpu") == []
    assert "XLA_FLAGS" not in env2
