"""Distributed pass registry tests (SURVEY.md §2.3 'Distributed passes')."""
import pytest

from paddle_tpu.distributed.passes import (
    new_pass, PassManager, PassBase, register_pass,
)


def test_registry_and_manager():
    pm = PassManager([
        new_pass("auto_parallel_amp", {"level": "O2"}),
        new_pass("auto_parallel_recompute", {"granularity": "full"}),
        new_pass("auto_parallel_sharding", {"stage": 3}),
        new_pass("pipeline_scheduler", {"schedule_mode": "1F1B",
                                        "accumulate_steps": 8}),
        new_pass("fuse_all_reduce"),
    ])
    assert "auto_parallel_amp" in pm.names
    plan = pm.apply({})
    assert plan["amp"]["dtype"] == "bfloat16"
    assert plan["amp"]["master_weights"]
    assert plan["recompute"]["enable"]
    assert plan["sharding"]["stage"] == 3
    assert plan["pipeline"]["accumulate_steps"] == 8
    assert any("XLA" in n for n in plan["notes"])


def test_unknown_pass_and_bad_schedule():
    with pytest.raises(ValueError, match="unknown pass"):
        new_pass("nope")
    p = new_pass("pipeline_scheduler", {"schedule_mode": "bogus"})
    with pytest.raises(ValueError, match="unknown pipeline schedule"):
        p.check({})


def test_custom_pass_registration():
    @register_pass("my_test_pass")
    class MyPass(PassBase):
        def apply(self, plan, *a, **kw):
            plan["custom"] = True
            return plan

    plan = PassManager([new_pass("my_test_pass")]).apply({})
    assert plan["custom"]
