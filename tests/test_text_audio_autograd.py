"""paddle.text (Viterbi), paddle.audio (spectrograms), incubate.autograd
(jvp/Jacobian/Hessian), incubate.asp tests (SURVEY.md §2.2)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.text import ViterbiDecoder, viterbi_decode
from paddle_tpu.audio import (Spectrogram, MelSpectrogram, LogMelSpectrogram,
                              MFCC, functional as AF)
from paddle_tpu.incubate import autograd as iag
from paddle_tpu.incubate import asp


# -- text -------------------------------------------------------------------

def _brute_viterbi(emis, trans, start, stop):
    t, n = emis.shape
    best, best_path = None, None
    import itertools
    for path in itertools.product(range(n), repeat=t):
        s = start[path[0]] + emis[0, path[0]]
        for i in range(1, t):
            s += trans[path[i - 1], path[i]] + emis[i, path[i]]
        s += stop[path[-1]]
        if best is None or s > best:
            best, best_path = s, path
    return best, list(best_path)


def test_viterbi_matches_bruteforce():
    rng = np.random.default_rng(0)
    n, t = 3, 4
    emis = rng.normal(size=(1, t, n)).astype(np.float32)
    trans_full = rng.normal(size=(n + 2, n + 2)).astype(np.float32)
    score, path = viterbi_decode(paddle.to_tensor(emis),
                                 paddle.to_tensor(trans_full))
    ref_score, ref_path = _brute_viterbi(
        emis[0], trans_full[:n, :n], trans_full[n, :n],
        trans_full[:n, n + 1])
    assert float(score.numpy()[0]) == pytest.approx(ref_score, abs=1e-5)
    np.testing.assert_array_equal(path.numpy()[0], ref_path)


def test_viterbi_decoder_layer_no_bos():
    rng = np.random.default_rng(1)
    emis = rng.normal(size=(2, 5, 4)).astype(np.float32)
    trans = rng.normal(size=(4, 4)).astype(np.float32)
    dec = ViterbiDecoder(paddle.to_tensor(trans), include_bos_eos_tag=False)
    score, path = dec(paddle.to_tensor(emis))
    assert score.shape == [2] and path.shape == [2, 5]
    assert path.numpy().min() >= 0 and path.numpy().max() < 4


# -- audio ------------------------------------------------------------------

def test_spectrogram_pure_tone():
    sr, n_fft = 1000, 128
    t = np.arange(sr) / sr
    freq = 125.0                        # exactly bin 16 of 128 @ sr 1000
    sig = np.sin(2 * np.pi * freq * t).astype(np.float32)
    spec = Spectrogram(n_fft=n_fft, hop_length=64)(
        paddle.to_tensor(sig[None]))
    s = spec.numpy()[0]                 # [bins, frames]
    peak_bin = s.mean(-1).argmax()
    assert peak_bin == round(freq * n_fft / sr)


def test_mel_and_logmel_and_mfcc_shapes():
    sig = paddle.to_tensor(np.random.default_rng(2).normal(
        size=(2, 2048)).astype(np.float32))
    mel = MelSpectrogram(sr=8000, n_fft=256, n_mels=32)(sig)
    assert mel.shape[0] == 2 and mel.shape[1] == 32
    logmel = LogMelSpectrogram(sr=8000, n_fft=256, n_mels=32)(sig)
    assert logmel.shape == mel.shape
    mfcc = MFCC(sr=8000, n_mfcc=13, n_mels=32, n_fft=256)(sig)
    assert mfcc.shape[1] == 13
    fb = AF.compute_fbank_matrix(8000, 256, 32)
    assert fb.shape == (32, 129)
    assert (fb >= 0).all()


# -- incubate.autograd ------------------------------------------------------

def test_jvp_vjp_consistency():
    def f(x):
        return (x ** 2).sum()

    x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
    v = paddle.to_tensor(np.array([1.0, 0.0, 0.0], np.float32))
    out, tang = iag.jvp(f, x, v)
    assert float(out.numpy()) == pytest.approx(14.0)
    assert float(tang.numpy()) == pytest.approx(2.0)   # d/dx1 = 2*x1*v1

    out2, grads = iag.vjp(f, x)
    np.testing.assert_allclose(grads[0].numpy(), [2.0, 4.0, 6.0])


def test_jacobian_hessian():
    def f(x):
        return x * x

    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    J = iag.Jacobian(f, x)
    np.testing.assert_allclose(J[:].numpy(), [[2.0, 0.0], [0.0, 4.0]])

    def g(x):
        return (x ** 3).sum()

    H = iag.Hessian(g, x)
    np.testing.assert_allclose(H[:].numpy(), [[6.0, 0.0], [0.0, 12.0]])


# -- incubate.asp -----------------------------------------------------------

def test_asp_prune_and_maintain():
    paddle.seed(0)
    model = paddle.nn.Linear(8, 8)
    masks = asp.prune_model(model)
    assert masks
    w = model.weight.numpy()
    assert asp.calculate_density(model.weight) == pytest.approx(0.5)
    # 2:4 pattern: every group of 4 along last dim has exactly 2 nonzeros
    groups = (w.reshape(-1, 4) != 0).sum(1)
    assert (groups == 2).all()

    opt = asp.decorate(paddle.optimizer.SGD(
        learning_rate=0.1, parameters=model.parameters()))
    loss = (model(paddle.randn([4, 8])) ** 2).mean()
    loss.backward()
    opt.step()
    w2 = model.weight.numpy()
    assert ((w2 != 0) == (w != 0)).all()   # sparsity pattern preserved
    asp._masks.clear()


def test_text_datasets(tmp_path):
    """Cache-resolving text datasets: synthetic UCIHousing trains a
    regressor; cache misses raise with the expected path; a locally
    built Imdb archive parses."""
    import io
    import os
    import tarfile

    import numpy as np
    import pytest

    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer as opt
    from paddle_tpu.io import DataLoader
    from paddle_tpu.text import UCIHousing, Imdb

    ds = UCIHousing(synthetic=64)
    model = nn.Linear(13, 1)
    o = opt.SGD(learning_rate=0.1, parameters=model.parameters())
    first = last = None
    for ep in range(5):
        for x, y in DataLoader(ds, batch_size=16):
            loss = ((model(x) - y) ** 2).mean()
            loss.backward(); o.step(); o.clear_grad()
            last = float(loss.numpy())
            if first is None:
                first = last
    assert last < first

    with pytest.raises(IOError, match="place the reference archive"):
        Imdb(data_file="/nonexistent/aclImdb_v1.tar.gz")

    # build a tiny archive in the Imdb layout and parse it
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tf:
        for i, (split, lab, txt) in enumerate([
                ("train", "pos", b"great movie loved it"),
                ("train", "neg", b"terrible waste of time"),
                ("test", "pos", b"fine")]):
            data = txt
            info = tarfile.TarInfo(f"aclImdb/{split}/{lab}/{i}.txt")
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    path = str(tmp_path / "test_imdb.tar.gz")
    with open(path, "wb") as f:
        f.write(buf.getvalue())
    imdb = Imdb(data_file=path, mode="train")
    assert len(imdb) == 2
    ids, label = imdb[0]
    assert label in (0, 1) and len(ids) == 4
    # train/test instances share word ids (whole-archive vocab)
    imdb_test = Imdb(data_file=path, mode="test")
    assert imdb_test.word_idx == imdb.word_idx


def test_audio_datasets(tmp_path):
    import numpy as np
    import pytest
    from paddle_tpu.audio import TESS, ESC50

    np.savez(tmp_path / "w.npz",
             waveforms=np.random.RandomState(0).rand(3, 400)
             .astype("float32"),
             labels=np.arange(3, dtype=np.int64))
    ds = TESS(data_file=str(tmp_path / "w.npz"))
    wav, lab = ds[2]
    assert wav.shape == (400,) and lab == 2 and len(ds) == 3
    with pytest.raises(IOError, match="place the pre-extracted"):
        ESC50(data_file=str(tmp_path / "missing.npz"))


def test_incubate_segment_alias():
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.incubate as inc

    out = inc.segment_sum(
        paddle.to_tensor(np.ones((4, 2), np.float32)),
        paddle.to_tensor(np.array([0, 0, 1, 1], np.int64)))
    np.testing.assert_allclose(np.asarray(out.numpy()),
                               [[2, 2], [2, 2]])


def test_round4_namespace_additions():
    """audio.datasets/backends, vision.image_load, utils.unique_name,
    autograd.jacobian/hessian facades, callbacks.ReduceLROnPlateau."""
    import numpy as np
    import paddle_tpu as paddle

    import paddle_tpu.audio as audio
    assert audio.datasets.TESS is not None
    assert audio.backends.list_available_backends() == ["wave"]
    # wav round-trip through the stdlib backend
    import wave, tempfile, os
    path = os.path.join(tempfile.mkdtemp(), "t.wav")
    sig = (np.sin(np.linspace(0, 40, 1600)) * 20000).astype(np.int16)
    with wave.open(path, "wb") as w:
        w.setnchannels(1); w.setsampwidth(2); w.setframerate(16000)
        w.writeframes(sig.tobytes())
    t, sr = audio.load(path)
    assert sr == 16000 and t.shape == [1, 1600]
    np.testing.assert_allclose(t.numpy()[0], sig / 32768.0, atol=1e-4)

    from paddle_tpu.utils import unique_name
    a, b = unique_name.generate("fc"), unique_name.generate("fc")
    assert a != b
    with unique_name.guard():
        assert unique_name.generate("fc") == "fc_0"
    with unique_name.guard("block1"):
        n1 = unique_name.generate("fc")
    with unique_name.guard("block2"):
        n2 = unique_name.generate("fc")
    assert n1 == "block1_fc_0" and n2 == "block2_fc_0"

    import paddle_tpu.autograd as ag
    f = lambda x: (x ** 2).sum()
    x = paddle.to_tensor(np.arange(3, dtype="float32"))
    h = ag.hessian(f, x)
    np.testing.assert_allclose(np.asarray(h.numpy()), np.eye(3) * 2,
                               atol=1e-5)
    j = ag.jacobian(f, x)
    np.testing.assert_allclose(np.asarray(j.numpy()).ravel(), [0, 2, 4],
                               atol=1e-5)

    import paddle_tpu.callbacks as cb
    r = cb.ReduceLROnPlateau(monitor="loss", patience=1, verbose=0)

    class FakeOpt:
        _lr = 0.1
        def get_lr(self): return self._lr
        def set_lr(self, v): self._lr = v
    class FakeModel:
        _optimizer = FakeOpt()
    r.model = FakeModel()
    r.on_eval_end({"loss": [1.0]})
    r.on_eval_end({"loss": [1.0]})   # no improvement -> patience hit
    assert abs(FakeModel._optimizer.get_lr() - 0.01) < 1e-9
    # cooldown holds further reductions
    r2 = cb.ReduceLROnPlateau(monitor="loss", patience=1, cooldown=2,
                              verbose=0)
    r2.model = FakeModel()
    FakeModel._optimizer.set_lr(0.1)
    r2.on_eval_end({"loss": [1.0]})
    r2.on_eval_end({"loss": [1.0]})          # reduce #1 -> cooldown starts
    assert abs(FakeModel._optimizer.get_lr() - 0.01) < 1e-9
    r2.on_eval_end({"loss": [1.0]})          # cooldown tick 1: no change
    r2.on_eval_end({"loss": [1.0]})          # cooldown tick 2: no change
    assert abs(FakeModel._optimizer.get_lr() - 0.01) < 1e-9
    import pytest as pt
    with pt.raises(NotImplementedError):
        cb.VisualDL()


def test_image_load_stdlib_png_decoder():
    """The zero-egress PNG path must agree with PIL on a round-trip."""
    import io, os, tempfile
    import numpy as np
    from PIL import Image
    import paddle_tpu.vision as vision

    rng = np.random.default_rng(0)
    img = rng.integers(0, 255, (13, 17, 3), np.uint8)
    path = os.path.join(tempfile.mkdtemp(), "t.png")
    Image.fromarray(img).save(path)
    # PIL-backed load
    np.testing.assert_array_equal(vision.image_load(path), img)
    # force the stdlib decoder
    import paddle_tpu.vision as v
    import builtins, unittest.mock as mock
    real_import = builtins.__import__
    def no_pil(name, *a, **k):
        if name == "PIL":
            raise ImportError("forced")
        return real_import(name, *a, **k)
    with mock.patch("builtins.__import__", side_effect=no_pil):
        got = v.image_load(path)
    np.testing.assert_array_equal(got, img)


def test_round4_text_datasets():
    """Movielens/WMT16/Conll05st parsers against synthetic archives in
    the canonical layouts (zero-egress: real archives unavailable)."""
    import io, os, tarfile, tempfile, zipfile
    import paddle_tpu.text as text

    tmp = tempfile.mkdtemp()

    # --- MovieLens-1M layout
    mlpath = os.path.join(tmp, "ml-1m.zip")
    with zipfile.ZipFile(mlpath, "w") as z:
        z.writestr("ml-1m/users.dat",
                   "1::M::25::4::12345\n2::F::35::7::54321\n")
        z.writestr("ml-1m/movies.dat",
                   "10::Toy Story (1995)::Animation|Comedy\n"
                   "20::Heat (1995)::Action\n")
        z.writestr("ml-1m/ratings.dat",
                   "1::10::5::978300760\n2::20::3::978302109\n"
                   "1::20::4::978301968\n")
    ds = text.Movielens(data_file=mlpath, mode="train")
    assert len(ds) == 3                      # 3 ratings, none in test split
    uid, g, age, occ, mid, cats, title, rating = ds[0]
    assert (uid, g, age, occ, mid, rating) == (1, 0, 2, 4, 10, 5.0)
    assert len(ds.categories_dict) == 3      # Animation, Comedy, Action

    # --- WMT16 layout (parallel .en/.de line files)
    wmtpath = os.path.join(tmp, "wmt16.tar.gz")
    with tarfile.open(wmtpath, "w:gz") as tf:
        for name, payload in [
                ("wmt16/train.en", "a cat sat\nthe dog ran\n"),
                ("wmt16/train.de", "eine katze sass\nder hund lief\n")]:
            data = payload.encode()
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    wmt = text.WMT16(data_file=wmtpath, mode="train", src_dict_size=50,
                     trg_dict_size=50)
    assert len(wmt) == 2
    src, trg_in, trg_out = wmt[0]
    assert trg_in[0] == text.WMT16.BOS and trg_out[-1] == text.WMT16.EOS
    assert len(src) == 3 and len(trg_in) == 4
    # de->en direction swaps the pair
    wmt_de = text.WMT16(data_file=wmtpath, mode="train", lang="de")
    assert [wmt.src_dict.get(w) is not None for w in ["a", "cat"]] == [True] * 2
    assert "katze" in wmt_de.src_dict

    # --- Conll05 layout (words + props column files)
    cpath = os.path.join(tmp, "conll05st-tests.tar.gz")
    with tarfile.open(cpath, "w:gz") as tf:
        for name, payload in [
                ("conll05st/test.wsj.words", "The\ncat\nsat\n\nDogs\nran\n\n"),
                ("conll05st/test.wsj.props", "- B-A0\n- I-A0\n sat B-V\n\n"
                                             "- B-A0\n ran B-V\n\n")]:
            data = payload.encode()
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    c = text.Conll05st(data_file=cpath, mode="test")
    assert len(c) == 2
    words, pred_idx, labs = c[0]
    assert len(words) == 3 and len(labs) == 3
    assert pred_idx == 2                      # 'sat' row carries the verb
    assert text.Conll05 is text.Conll05st

    # train/test WMT vocab must share word ids (vocab from train pair)
    with tarfile.open(os.path.join(tmp, "wmt16b.tar.gz"), "w:gz") as tf:
        for name, payload in [
                ("wmt16/train.en", "a cat sat\nthe dog ran\n"),
                ("wmt16/train.de", "eine katze sass\nder hund lief\n"),
                ("wmt16/test.en", "dog sat\n"),
                ("wmt16/test.de", "hund sass\n")]:
            data = payload.encode()
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    tr = text.WMT16(data_file=os.path.join(tmp, "wmt16b.tar.gz"),
                    mode="train")
    te = text.WMT16(data_file=os.path.join(tmp, "wmt16b.tar.gz"),
                    mode="test")
    assert tr.src_dict == te.src_dict and tr.trg_dict == te.trg_dict
