"""paddle.text (Viterbi), paddle.audio (spectrograms), incubate.autograd
(jvp/Jacobian/Hessian), incubate.asp tests (SURVEY.md §2.2)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.text import ViterbiDecoder, viterbi_decode
from paddle_tpu.audio import (Spectrogram, MelSpectrogram, LogMelSpectrogram,
                              MFCC, functional as AF)
from paddle_tpu.incubate import autograd as iag
from paddle_tpu.incubate import asp


# -- text -------------------------------------------------------------------

def _brute_viterbi(emis, trans, start, stop):
    t, n = emis.shape
    best, best_path = None, None
    import itertools
    for path in itertools.product(range(n), repeat=t):
        s = start[path[0]] + emis[0, path[0]]
        for i in range(1, t):
            s += trans[path[i - 1], path[i]] + emis[i, path[i]]
        s += stop[path[-1]]
        if best is None or s > best:
            best, best_path = s, path
    return best, list(best_path)


def test_viterbi_matches_bruteforce():
    rng = np.random.default_rng(0)
    n, t = 3, 4
    emis = rng.normal(size=(1, t, n)).astype(np.float32)
    trans_full = rng.normal(size=(n + 2, n + 2)).astype(np.float32)
    score, path = viterbi_decode(paddle.to_tensor(emis),
                                 paddle.to_tensor(trans_full))
    ref_score, ref_path = _brute_viterbi(
        emis[0], trans_full[:n, :n], trans_full[n, :n],
        trans_full[:n, n + 1])
    assert float(score.numpy()[0]) == pytest.approx(ref_score, abs=1e-5)
    np.testing.assert_array_equal(path.numpy()[0], ref_path)


def test_viterbi_decoder_layer_no_bos():
    rng = np.random.default_rng(1)
    emis = rng.normal(size=(2, 5, 4)).astype(np.float32)
    trans = rng.normal(size=(4, 4)).astype(np.float32)
    dec = ViterbiDecoder(paddle.to_tensor(trans), include_bos_eos_tag=False)
    score, path = dec(paddle.to_tensor(emis))
    assert score.shape == [2] and path.shape == [2, 5]
    assert path.numpy().min() >= 0 and path.numpy().max() < 4


# -- audio ------------------------------------------------------------------

def test_spectrogram_pure_tone():
    sr, n_fft = 1000, 128
    t = np.arange(sr) / sr
    freq = 125.0                        # exactly bin 16 of 128 @ sr 1000
    sig = np.sin(2 * np.pi * freq * t).astype(np.float32)
    spec = Spectrogram(n_fft=n_fft, hop_length=64)(
        paddle.to_tensor(sig[None]))
    s = spec.numpy()[0]                 # [bins, frames]
    peak_bin = s.mean(-1).argmax()
    assert peak_bin == round(freq * n_fft / sr)


def test_mel_and_logmel_and_mfcc_shapes():
    sig = paddle.to_tensor(np.random.default_rng(2).normal(
        size=(2, 2048)).astype(np.float32))
    mel = MelSpectrogram(sr=8000, n_fft=256, n_mels=32)(sig)
    assert mel.shape[0] == 2 and mel.shape[1] == 32
    logmel = LogMelSpectrogram(sr=8000, n_fft=256, n_mels=32)(sig)
    assert logmel.shape == mel.shape
    mfcc = MFCC(sr=8000, n_mfcc=13, n_mels=32, n_fft=256)(sig)
    assert mfcc.shape[1] == 13
    fb = AF.compute_fbank_matrix(8000, 256, 32)
    assert fb.shape == (32, 129)
    assert (fb >= 0).all()


# -- incubate.autograd ------------------------------------------------------

def test_jvp_vjp_consistency():
    def f(x):
        return (x ** 2).sum()

    x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
    v = paddle.to_tensor(np.array([1.0, 0.0, 0.0], np.float32))
    out, tang = iag.jvp(f, x, v)
    assert float(out.numpy()) == pytest.approx(14.0)
    assert float(tang.numpy()) == pytest.approx(2.0)   # d/dx1 = 2*x1*v1

    out2, grads = iag.vjp(f, x)
    np.testing.assert_allclose(grads[0].numpy(), [2.0, 4.0, 6.0])


def test_jacobian_hessian():
    def f(x):
        return x * x

    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    J = iag.Jacobian(f, x)
    np.testing.assert_allclose(J[:].numpy(), [[2.0, 0.0], [0.0, 4.0]])

    def g(x):
        return (x ** 3).sum()

    H = iag.Hessian(g, x)
    np.testing.assert_allclose(H[:].numpy(), [[6.0, 0.0], [0.0, 12.0]])


# -- incubate.asp -----------------------------------------------------------

def test_asp_prune_and_maintain():
    paddle.seed(0)
    model = paddle.nn.Linear(8, 8)
    masks = asp.prune_model(model)
    assert masks
    w = model.weight.numpy()
    assert asp.calculate_density(model.weight) == pytest.approx(0.5)
    # 2:4 pattern: every group of 4 along last dim has exactly 2 nonzeros
    groups = (w.reshape(-1, 4) != 0).sum(1)
    assert (groups == 2).all()

    opt = asp.decorate(paddle.optimizer.SGD(
        learning_rate=0.1, parameters=model.parameters()))
    loss = (model(paddle.randn([4, 8])) ** 2).mean()
    loss.backward()
    opt.step()
    w2 = model.weight.numpy()
    assert ((w2 != 0) == (w != 0)).all()   # sparsity pattern preserved
    asp._masks.clear()


def test_text_datasets(tmp_path):
    """Cache-resolving text datasets: synthetic UCIHousing trains a
    regressor; cache misses raise with the expected path; a locally
    built Imdb archive parses."""
    import io
    import os
    import tarfile

    import numpy as np
    import pytest

    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer as opt
    from paddle_tpu.io import DataLoader
    from paddle_tpu.text import UCIHousing, Imdb

    ds = UCIHousing(synthetic=64)
    model = nn.Linear(13, 1)
    o = opt.SGD(learning_rate=0.1, parameters=model.parameters())
    first = last = None
    for ep in range(5):
        for x, y in DataLoader(ds, batch_size=16):
            loss = ((model(x) - y) ** 2).mean()
            loss.backward(); o.step(); o.clear_grad()
            last = float(loss.numpy())
            if first is None:
                first = last
    assert last < first

    with pytest.raises(IOError, match="place the reference archive"):
        Imdb(data_file="/nonexistent/aclImdb_v1.tar.gz")

    # build a tiny archive in the Imdb layout and parse it
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tf:
        for i, (split, lab, txt) in enumerate([
                ("train", "pos", b"great movie loved it"),
                ("train", "neg", b"terrible waste of time"),
                ("test", "pos", b"fine")]):
            data = txt
            info = tarfile.TarInfo(f"aclImdb/{split}/{lab}/{i}.txt")
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    path = str(tmp_path / "test_imdb.tar.gz")
    with open(path, "wb") as f:
        f.write(buf.getvalue())
    imdb = Imdb(data_file=path, mode="train")
    assert len(imdb) == 2
    ids, label = imdb[0]
    assert label in (0, 1) and len(ids) == 4
    # train/test instances share word ids (whole-archive vocab)
    imdb_test = Imdb(data_file=path, mode="test")
    assert imdb_test.word_idx == imdb.word_idx


def test_audio_datasets(tmp_path):
    import numpy as np
    import pytest
    from paddle_tpu.audio import TESS, ESC50

    np.savez(tmp_path / "w.npz",
             waveforms=np.random.RandomState(0).rand(3, 400)
             .astype("float32"),
             labels=np.arange(3, dtype=np.int64))
    ds = TESS(data_file=str(tmp_path / "w.npz"))
    wav, lab = ds[2]
    assert wav.shape == (400,) and lab == 2 and len(ds) == 3
    with pytest.raises(IOError, match="place the pre-extracted"):
        ESC50(data_file=str(tmp_path / "missing.npz"))


def test_incubate_segment_alias():
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.incubate as inc

    out = inc.segment_sum(
        paddle.to_tensor(np.ones((4, 2), np.float32)),
        paddle.to_tensor(np.array([0, 0, 1, 1], np.int64)))
    np.testing.assert_allclose(np.asarray(out.numpy()),
                               [[2, 2], [2, 2]])
