"""Static control flow: paddle.static.nn.cond / while_loop / switch_case
(VERDICT.md round-1 item 9; reference:
``python/paddle/static/nn/control_flow.py`` + the dy2static ifelse/while
converters — here they lower to lax.cond / lax.while_loop / lax.switch so
tensor-dependent branches compile instead of graph-breaking)."""
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.static import nn as snn


def t(v):
    return paddle.to_tensor(np.asarray(v, np.float32))


def test_cond_eager():
    out = snn.cond(t(1.0) > 0, lambda: t([1.0]), lambda: t([2.0]))
    np.testing.assert_allclose(out.numpy(), [1.0])
    out = snn.cond(t(-1.0) > 0, lambda: t([1.0]), lambda: t([2.0]))
    np.testing.assert_allclose(out.numpy(), [2.0])


def test_cond_compiled_no_graph_break():
    """A tensor-dependent branch inside @to_static stays compiled — no
    graph-break warning, correct both ways."""
    @paddle.jit.to_static
    def branchy(x):
        s = x.sum()
        return snn.cond(s > 0, lambda: x * 2.0, lambda: x - 1.0)

    with warnings.catch_warnings():
        warnings.simplefilter("error")     # any graph-break warns -> fail
        pos = branchy(t([1.0, 2.0]))
        neg = branchy(t([-1.0, -2.0]))
    np.testing.assert_allclose(pos.numpy(), [2.0, 4.0])
    np.testing.assert_allclose(neg.numpy(), [-2.0, -3.0])


def test_cond_grad_eager_and_compiled():
    x = t([3.0])
    x.stop_gradient = False
    out = snn.cond((x > 0).all(), lambda: (x * x).sum(), lambda: x.sum())
    out.backward()
    np.testing.assert_allclose(x.grad.numpy(), [6.0])

    class Branchy(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = paddle.nn.Linear(2, 2)

        def forward(self, x):
            y = self.lin(x)
            return snn.cond(y.sum() > 0, lambda: (y * y).sum(),
                            lambda: y.sum())

    m = paddle.jit.to_static(Branchy())
    xx = t([[1.0, 2.0]])
    loss = m(xx)
    loss.backward()      # grads flow through lax.cond via the outer vjp
    assert m.lin.weight.grad is not None


def test_while_loop_eager_and_compiled():
    def cond_fn(i, s):
        return i < 5

    def body_fn(i, s):
        return i + 1, s + i

    i, s = snn.while_loop(cond_fn, body_fn, [t(0.0), t(0.0)])
    np.testing.assert_allclose(s.numpy(), 10.0)    # 0+1+2+3+4

    @paddle.jit.to_static
    def f(n):
        i, s = snn.while_loop(lambda i, s: i < n, body_fn,
                              [paddle.zeros([]), paddle.zeros([])])
        return s

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        out = f(t(5.0))
    np.testing.assert_allclose(out.numpy(), 10.0)


def test_switch_case_and_case():
    fns = [lambda: t([10.0]), lambda: t([20.0]), lambda: t([30.0])]
    np.testing.assert_allclose(
        snn.switch_case(paddle.to_tensor(1), fns).numpy(), [20.0])

    @paddle.jit.to_static
    def f(i):
        return snn.switch_case(i, fns, default=lambda: t([99.0]))

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        np.testing.assert_allclose(f(paddle.to_tensor(2)).numpy(), [30.0])
        np.testing.assert_allclose(f(paddle.to_tensor(7)).numpy(), [99.0])

    out = snn.case([(t(0.0) > 1, lambda: t([1.0])),
                    (t(2.0) > 1, lambda: t([2.0]))],
                   default=lambda: t([3.0]))
    np.testing.assert_allclose(out.numpy(), [2.0])


def test_graph_break_retries_before_latching():
    """One transient tracer error must not permanently latch eager."""
    from paddle_tpu.jit.api import StaticFunction

    fail_once = {"n": 0}

    def flaky(x):
        if fail_once["n"] == 0:
            fail_once["n"] += 1
            if float(x.sum().numpy()) > -1e9:   # tracer bool -> graph break
                pass
        return x * 2.0

    sf = StaticFunction(flaky)
    xx = t([1.0, 2.0])
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        out1 = sf(xx)                     # breaks (eager result), retry armed
        out2 = sf(xx)                     # compiles clean this time
    np.testing.assert_allclose(out1.numpy(), [2.0, 4.0])
    np.testing.assert_allclose(out2.numpy(), [2.0, 4.0])
    entry = list(sf._cache.values())[0]
    assert not entry["fallback"] and entry["breaks"] == 0  # reset on success


def test_persistently_dynamic_latches():
    def dynamic(x):
        if float(x.sum().numpy()) > 0:    # always concretizes -> break
            return x * 2.0
        return x

    from paddle_tpu.jit.api import StaticFunction
    sf = StaticFunction(dynamic)
    xx = t([1.0])
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        sf(xx)
        sf(xx)
        out = sf(xx)
    np.testing.assert_allclose(out.numpy(), [2.0])
    entry = list(sf._cache.values())[0]
    assert entry["fallback"] and entry["breaks"] == 2
