"""Device-tier decode speed (ISSUE 16): q-block ragged attention grid,
int8 weights end-to-end, and batched drafting.

Three layers, one bar each:

* the fixed-q-block ragged kernel replays the per-token kernel's exact
  online-softmax recurrence on every descriptor layout (straddling
  spans, pure decode, shared-prefix page aliasing, int8-KV pages,
  padded tail blocks): outputs agree to ~1 ulp — the only reorder is
  the MXU dot shape itself — and greedy token streams through the
  engine are BIT-identical between the two grids;
* ``quantize_linears`` + ``weight_dtype="int8"`` routes Linear forwards
  through the int8 GEMM and the fully-quantized serving config is
  bit-stable across same-seed runs (ledger token-stream attestation);
* ``DraftModelDrafter.propose_batch`` drafts for every live sequence in
  one padded forward per step, bit-identical to per-sequence
  ``propose``, inside a power-of-two compiled-program family.
"""
import threading

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import ContinuousServingEngine
from paddle_tpu.models import LlamaForCausalLM, llama_tiny
from paddle_tpu.models.generation import quantize_kv_rows
from paddle_tpu.ops.pallas.ragged_paged_attention import (
    ragged_paged_attention, ragged_paged_attention_reference,
    qblock_schedule, DEFAULT_QBLOCK, _qblock_rows, _token_descriptors,
    _ragged_paged_attention_pallas, _ragged_paged_attention_pallas_quant,
    _ragged_paged_attention_pallas_qblock)


# ---------------------------------------------------------------------------
# kernel parity: q-block grid vs per-token grid (bitwise) vs dense oracle
# ---------------------------------------------------------------------------

def _pool(nslots=4, pages_per_seq=4, page=8, kv_heads=2, d=32, seed=0):
    rng = np.random.RandomState(seed)
    npages = nslots * pages_per_seq + 1          # page 0 = scratch
    kp = jnp.asarray(rng.randn(kv_heads, npages, page, d), jnp.float32)
    vp = jnp.asarray(rng.randn(kv_heads, npages, page, d), jnp.float32)
    tbl = np.zeros((nslots, pages_per_seq), np.int32)
    for s in range(nslots):
        tbl[s] = np.arange(1 + s * pages_per_seq,
                           1 + (s + 1) * pages_per_seq)
    return kp, vp, tbl


#: q-block vs per-token kernel tolerance: the grids run the SAME
#: recurrence in the same per-row page order, but the q-block MXU dot is
#: [q_block*group, d] where the per-token dot is [group, d] — different
#: tile shapes accumulate the d-reduction in different orders, worth ~1
#: ulp (<1e-7 observed). A masking bug would be O(1), int8-KV error
#: ~1e-2, so 1e-6 still proves the recurrence is the same one.
KERNEL_TOL = dict(rtol=1e-6, atol=1e-6)


def _parity(layout, tokens=None, q_block=8, heads=4, d=32, seed=0,
            tbl_edit=None, quant=False):
    """Run the SAME descriptors through the q-block and per-token
    interpret kernels: span rows must agree to KERNEL_TOL (~1 ulp — the
    q-block grid replays the per-token online-softmax recurrence
    job-by-job in the same order; see KERNEL_TOL for why not bitwise)
    and match the dense reference to float tolerance."""
    kp, vp, tbl = _pool(nslots=max(x[0] for x in layout) + 1, d=d,
                        seed=seed)
    if tbl_edit is not None:
        tbl_edit(tbl)
    seq_slots = np.asarray([x[0] for x in layout], np.int32)
    q_starts = np.asarray([x[1] for x in layout], np.int32)
    q_lens = np.asarray([x[2] for x in layout], np.int32)
    ctx = np.asarray([x[3] for x in layout], np.int32)
    T = tokens or int((q_starts + q_lens).max())
    rng = np.random.RandomState(seed + 1)
    q = jnp.asarray(rng.randn(T, heads, d), jnp.float32)
    sm = d ** -0.5
    if quant:
        kq, ks = quantize_kv_rows(np.asarray(kp))
        vq, vs = quantize_kv_rows(np.asarray(vp))
        kq, ks = jnp.asarray(kq), jnp.asarray(ks)
        vq, vs = jnp.asarray(vq), jnp.asarray(vs)
        qb = np.asarray(_ragged_paged_attention_pallas_qblock(
            q, kq, vq, jnp.asarray(tbl), seq_slots, q_starts, q_lens, ctx,
            sm_scale=sm, interpret=True, k_scales=ks, v_scales=vs,
            q_block=q_block))
        ts, tc = _token_descriptors(T, seq_slots, q_starts, q_lens, ctx)
        tok = np.asarray(_ragged_paged_attention_pallas_quant(
            q, kq, vq, ks, vs, jnp.asarray(tbl), ts, tc,
            sm_scale=sm, interpret=True))
        ref_tol = dict(rtol=5e-2, atol=5e-2)    # int8 quantization error
    else:
        qb = np.asarray(_ragged_paged_attention_pallas_qblock(
            q, kp, vp, jnp.asarray(tbl), seq_slots, q_starts, q_lens, ctx,
            sm_scale=sm, interpret=True, q_block=q_block))
        ts, tc = _token_descriptors(T, seq_slots, q_starts, q_lens, ctx)
        tok = np.asarray(_ragged_paged_attention_pallas(
            q, kp, vp, jnp.asarray(tbl), ts, tc, sm_scale=sm,
            interpret=True))
        ref_tol = dict(rtol=2e-5, atol=2e-5)
    ref = np.asarray(ragged_paged_attention_reference(
        q, kp, vp, tbl, seq_slots, q_starts, q_lens, ctx))
    for slot, qs, ql, _ in layout:               # pad rows are garbage
        np.testing.assert_allclose(qb[qs:qs + ql], tok[qs:qs + ql],
                                   **KERNEL_TOL)
        assert np.isfinite(qb[qs:qs + ql]).all()
        np.testing.assert_allclose(qb[qs:qs + ql], ref[qs:qs + ql],
                                   **ref_tol)
    return qb, tok


def test_qblock_straddling_spans_parity():
    # spans crossing q-block boundaries: a 9-token prefill straddles
    # blocks 0→1, a 6-token chunk straddles 1→2 — each block mixes rows
    # of different owners, the masking worst case
    _parity([(0, 0, 1, 31), (1, 1, 9, 25), (2, 10, 6, 6), (3, 16, 1, 4)],
            q_block=8)


def test_qblock_pure_decode_parity():
    # the continuous-batching steady state: every span is one token, so
    # one q block carries up to q_block distinct owners
    _parity([(0, 0, 1, 7), (1, 1, 1, 19), (2, 2, 1, 32), (3, 3, 1, 1)],
            q_block=8)


def test_qblock_shared_prefix_aliased_pages():
    # slot 1's table aliases slot 0's leading pages (a prefix-cache
    # hit): the job list must walk the aliased page once per owner
    def alias(tbl):
        tbl[1, :2] = tbl[0, :2]
    _parity([(0, 0, 1, 20), (1, 1, 3, 19)], tbl_edit=alias, seed=7)


def test_qblock_padded_tail_blocks():
    # tokens=24 with spans ending at 10: blocks 1..2 are pure padding
    # (row slot -1, one sentinel job) — they must stay finite and never
    # poison the valid rows
    _parity([(0, 0, 4, 12), (1, 4, 6, 6)], tokens=24, q_block=8)


def test_qblock_int8_kv_parity():
    # int8 KV pages: the q-block quant kernel dequantizes per row-scale
    # exactly like the per-token quant kernel — same KERNEL_TOL parity
    _parity([(0, 0, 1, 12), (1, 1, 5, 25), (2, 6, 9, 9)], quant=True)


def test_qblock_small_block_size():
    # q_block smaller than most spans: every span straddles
    _parity([(0, 0, 7, 15), (1, 7, 5, 5), (2, 12, 1, 30)], q_block=2)


def test_qblock_schedule_contract():
    """Sentinels, ordering, and pow2 job padding of the host schedule."""
    kp, vp, tbl = _pool(nslots=3, page=8)
    seq_slots = np.asarray([0, 1, 2], np.int32)
    q_starts = np.asarray([0, 1, 10], np.int32)
    q_lens = np.asarray([1, 9, 6], np.int32)
    ctx = np.asarray([33, 25, 6], np.int32)
    row_slot, row_ctx, job_page, job_slot, job_kv = qblock_schedule(
        17, seq_slots, q_starts, q_lens, ctx, tbl, 8, 8)
    assert row_slot.shape == (24,)               # ceil(17/8)*8
    # block-pad rows (slot -1 / ctx 0) differ from pad jobs (slot -2)
    np.testing.assert_array_equal(row_slot[17:], -1)
    np.testing.assert_array_equal(row_ctx[17:], 0)
    B, J = job_page.shape
    assert B == 3 and J & (J - 1) == 0           # pow2 job bucket
    # pad jobs use the sentinel slot -2 and the scratch page 0
    assert (job_page[job_slot == -2] == 0).all()
    # every real job's page comes from its owner's block table, kv
    # offsets ascend per owner in page order
    for b in range(B):
        for j in range(J):
            s = int(job_slot[b, j])
            if s < 0:
                continue
            p = int(job_kv[b, j]) // 8
            assert job_page[b, j] == tbl[s, p]
    # decode-only blocks stop at each owner's context, not the table end
    _, _, jp2, js2, _ = qblock_schedule(
        3, np.arange(3, dtype=np.int32), np.arange(3, dtype=np.int32),
        np.ones(3, np.int32), np.asarray([7, 19, 30], np.int32), tbl, 8, 8)
    real = int((js2[0] >= 0).sum())
    assert real == 1 + 3 + 4                     # ceil(7/8)+ceil(19/8)+ceil(30/8)


def test_qblock_rows_env_knob(monkeypatch):
    """PADDLE_TPU_RAGGED_QBLOCK tunes the block size; junk values fall
    back to DEFAULT_QBLOCK; the public entry keeps KERNEL_TOL parity
    with the per-token grid at any block size."""
    assert _qblock_rows() == DEFAULT_QBLOCK == 8
    monkeypatch.setenv("PADDLE_TPU_RAGGED_QBLOCK", "4")
    assert _qblock_rows() == 4
    monkeypatch.setenv("PADDLE_TPU_RAGGED_QBLOCK", "notanint")
    assert _qblock_rows() == DEFAULT_QBLOCK
    monkeypatch.setenv("PADDLE_TPU_RAGGED_QBLOCK", "5")   # odd size
    kp, vp, tbl = _pool(nslots=3)
    seq_slots = np.asarray([0, 1, 2], np.int32)
    q_starts = np.asarray([0, 1, 8], np.int32)
    q_lens = np.asarray([1, 7, 4], np.int32)
    ctx = np.asarray([17, 22, 4], np.int32)
    rng = np.random.RandomState(11)
    q = jnp.asarray(rng.randn(12, 4, 32), jnp.float32)
    out = np.asarray(ragged_paged_attention(
        q, kp, vp, jnp.asarray(tbl), seq_slots, q_starts, q_lens, ctx,
        interpret=True))
    ts, tc = _token_descriptors(12, seq_slots, q_starts, q_lens, ctx)
    tok = np.asarray(_ragged_paged_attention_pallas(
        q, kp, vp, jnp.asarray(tbl), ts, tc, sm_scale=32 ** -0.5,
        interpret=True))
    np.testing.assert_allclose(out, tok, **KERNEL_TOL)


def test_ragged_impl_env_dispatch(monkeypatch):
    """PADDLE_TPU_RAGGED_IMPL selects the grid: "qblock" (the default
    under "auto") and "token" (per-token escape hatch) agree to
    KERNEL_TOL through the public entry; "xla" to float tolerance."""
    kp, vp, tbl = _pool(nslots=3)
    seq_slots = np.asarray([0, 1, 2], np.int32)
    q_starts = np.asarray([0, 1, 6], np.int32)
    q_lens = np.asarray([1, 5, 9], np.int32)
    ctx = np.asarray([19, 25, 9], np.int32)
    rng = np.random.RandomState(5)
    q = jnp.asarray(rng.randn(15, 4, 32), jnp.float32)

    def run():
        return np.asarray(ragged_paged_attention(
            q, kp, vp, jnp.asarray(tbl), seq_slots, q_starts, q_lens,
            ctx, interpret=True))

    monkeypatch.setenv("PADDLE_TPU_RAGGED_IMPL", "qblock")
    out_qb = run()
    monkeypatch.setenv("PADDLE_TPU_RAGGED_IMPL", "token")
    out_tok = run()
    monkeypatch.setenv("PADDLE_TPU_RAGGED_IMPL", "xla")
    out_xla = run()
    np.testing.assert_allclose(out_qb, out_tok, **KERNEL_TOL)
    np.testing.assert_allclose(out_qb[:12], out_xla[:12],
                               rtol=2e-5, atol=2e-5)


def test_qblock_traced_descriptors_fall_back():
    """The q-block schedule needs concrete descriptor values (host-side
    numpy); under jit tracing the public entry must quietly fall back to
    the per-token grid and stay correct."""
    kp, vp, tbl = _pool(nslots=2)
    seq_slots = np.asarray([0, 1], np.int32)
    q_starts = np.asarray([0, 4], np.int32)
    q_lens = np.asarray([4, 3], np.int32)
    ctx = np.asarray([12, 3], np.int32)
    rng = np.random.RandomState(9)
    q = jnp.asarray(rng.randn(7, 4, 32), jnp.float32)

    @jax.jit
    def f(q, ss, qs, ql, cx):
        return ragged_paged_attention(q, kp, vp, jnp.asarray(tbl),
                                      ss, qs, ql, cx, interpret=True)

    out = np.asarray(f(q, seq_slots, q_starts, q_lens, ctx))
    ref = np.asarray(ragged_paged_attention_reference(
        q, kp, vp, tbl, seq_slots, q_starts, q_lens, ctx))
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# engine acceptance: q-block grid == per-token grid, bit for bit
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    return LlamaForCausalLM(llama_tiny(num_hidden_layers=2,
                                       max_position_embeddings=256))


def _oracle(model, p, n):
    return np.asarray(model.generate(paddle.to_tensor(p),
                                     max_new_tokens=n)._data)


def _drive(eng, prompts, new_tokens):
    results = [None] * len(prompts)
    with eng:
        threads = [threading.Thread(
            target=lambda i=i, p=p: results.__setitem__(
                i, np.asarray(eng.generate(p, max_new_tokens=new_tokens,
                                           timeout=300).numpy())))
            for i, p in enumerate(prompts)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    return results


def test_engine_qblock_vs_token_bit_identical(model, monkeypatch):
    """Acceptance bar: a mixed chunked-prefill + decode workload under
    the q-block grid produces greedy outputs bit-identical to the
    per-token grid — and matches the dense oracle."""
    rng = np.random.RandomState(1)
    prompts = [rng.randint(0, 128, (1, n)).astype(np.int64)
               for n in (23, 5, 37, 11)]

    def run(impl):
        monkeypatch.setenv("PADDLE_TPU_RAGGED_IMPL", impl)
        eng = ContinuousServingEngine(
            model, max_batch_size=4, max_len=64, token_budget=16,
            prefill_chunk_tokens=16)
        out = _drive(eng, prompts, 5)
        assert eng.ragged_steps > 0
        return out

    got_qb = run("qblock")
    got_tok = run("token")
    for a, b in zip(got_qb, got_tok):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(got_qb[0], _oracle(model, prompts[0], 5))


# ---------------------------------------------------------------------------
# int8 weights end-to-end
# ---------------------------------------------------------------------------

def test_quantize_linears_routes_and_bounds_error():
    """quantize_linears snapshots every Linear's int8 weights, keeps the
    master copy consistent (dequantized), and eval-mode forwards route
    through int8_linear with bounded quantization error."""
    from paddle_tpu import nn
    from paddle_tpu.quantization import quantize_linears, int8_linear

    paddle.seed(3)
    net = nn.Sequential(nn.Linear(32, 48), nn.ReLU(), nn.Linear(48, 16))
    rng = np.random.RandomState(3)
    x = paddle.to_tensor(rng.randn(4, 32).astype(np.float32))
    net.eval()
    ref = np.asarray(net(x)._data)               # float forward
    lin0 = net[0]
    w_before = np.asarray(lin0.weight._data).copy()
    n = quantize_linears(net)
    assert n == 2
    assert lin0._w_int8 is not None and lin0._w_int8.dtype == np.int8
    # per-column absmax quantization: error <= scale/2 per element
    w_after = np.asarray(lin0.weight._data)
    assert np.abs(w_after - w_before).max() <= lin0._w_scale.max() * 0.5 + 1e-6
    # eval forward now routes through the int8 GEMM and equals the
    # explicit int8_linear call bit-for-bit
    out = np.asarray(net(x)._data)
    manual = np.asarray(net[2].forward(
        paddle.nn.functional.relu(int8_linear(
            x, lin0._w_int8, lin0._w_scale, lin0.bias)))._data)
    np.testing.assert_array_equal(out, manual)
    # quantization moves the output by at most the int8 error budget
    np.testing.assert_allclose(out, ref, rtol=0.1, atol=0.1)
    # idempotent: a second call quantizes nothing new
    assert quantize_linears(net) == 0
    # deterministic: repeat forward is bit-identical
    np.testing.assert_array_equal(out, np.asarray(net(x)._data))


def test_engine_weight_dtype_knob(monkeypatch):
    """PADDLE_WEIGHT_DTYPE=int8 quantizes at engine construction; junk
    values are rejected up front."""
    paddle.seed(0)
    m = LlamaForCausalLM(llama_tiny(num_hidden_layers=1))
    monkeypatch.setenv("PADDLE_WEIGHT_DTYPE", "int8")
    eng = ContinuousServingEngine(m, max_batch_size=2, max_len=48)
    assert eng.weight_dtype == "int8"
    assert eng.quantized_linears > 0
    monkeypatch.setenv("PADDLE_WEIGHT_DTYPE", "int4")
    with pytest.raises(ValueError):
        ContinuousServingEngine(m, max_batch_size=2, max_len=48)


def test_fully_int8_serving_bit_stable_with_attestation():
    """The fully-quantized device-tier config — int8 weights AND int8 KV
    pages on the q-block grid — is bit-stable: two same-seed engine runs
    deliver identical tokens, attested by identical ledger token-stream
    digests."""
    from paddle_tpu.profiler import ledger, request_trace as rt

    def run_once():
        paddle.seed(0)
        m = LlamaForCausalLM(llama_tiny(num_hidden_layers=1))
        eng = ContinuousServingEngine(
            m, max_batch_size=2, max_len=48, token_budget=16,
            prefill_chunk_tokens=16, weight_dtype="int8", kv_dtype="int8")
        rng = np.random.RandomState(0)
        prompts = [rng.randint(0, 128, (1, n)).astype(np.int64)
                   for n in (13, 21)]
        traces = [rt.start_request(prompt_tokens=p.shape[1],
                                   max_new_tokens=4) for p in prompts]
        outs = [None] * len(prompts)
        with eng:
            threads = [threading.Thread(
                target=lambda i=i: outs.__setitem__(
                    i, np.asarray(eng.generate(
                        prompts[i], max_new_tokens=4, timeout=300,
                        trace=traces[i]).numpy())))
                for i in range(len(prompts))]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        digs = [ledger.stream_digest(t.trace_id, 0) for t in traces]
        assert eng.quantized_linears > 0
        assert eng.ragged_buckets_used <= eng.declared_token_buckets()
        return outs, digs

    ledger.enable(mode="warn")
    try:
        outs_a, digs_a = run_once()
        outs_b, digs_b = run_once()
    finally:
        ledger.disable()
    for a, b in zip(outs_a, outs_b):
        np.testing.assert_array_equal(a, b)
    assert all(d is not None for d in digs_a)
    assert digs_a == digs_b


# ---------------------------------------------------------------------------
# batched drafting
# ---------------------------------------------------------------------------

def _draft_model(seed=7):
    paddle.seed(seed)
    return LlamaForCausalLM(llama_tiny(num_hidden_layers=1,
                                       vocab_size=97, hidden_size=32,
                                       intermediate_size=64))


def test_propose_batch_bit_identical_fewer_forwards():
    """propose_batch == per-sequence propose, bit for bit, with one
    forward per draft STEP instead of one per sequence per step."""
    from paddle_tpu.inference.speculative import DraftModelDrafter

    m = _draft_model()
    rng = np.random.RandomState(4)
    hists = [rng.randint(0, 97, n).tolist() for n in (9, 3, 17, 1)]
    ks = [3, 0, 2, 4]
    solo = DraftModelDrafter(m, window=16)
    want = [solo.propose(h, k) for h, k in zip(hists, ks)]
    batch = DraftModelDrafter(m, window=16)
    got = batch.propose_batch(hists, ks)
    assert got == want
    assert len(got[3]) == 4 and got[1] == []
    assert solo.forwards == sum(ks)              # 9
    assert batch.forwards == max(ks)             # 4: one per step


def test_propose_batch_prefix_stable():
    """Over-asking then trimming equals asking exactly — the engine
    over-asks with an optimistic cap and trims to sequential room."""
    from paddle_tpu.inference.speculative import DraftModelDrafter

    m = _draft_model(seed=42)
    rng = np.random.RandomState(8)
    hists = [rng.randint(0, 97, n).tolist() for n in (7, 12)]
    d = DraftModelDrafter(m, window=16)
    long = d.propose_batch(hists, [5, 5])
    short = d.propose_batch(hists, [2, 3])
    assert long[0][:2] == short[0] and long[1][:3] == short[1]


def test_propose_batch_pow2_program_family():
    """Every draft forward runs a power-of-two (rows, width) shape with
    width capped at the drafter window — a bounded compiled-program
    family, not per-(batch, length) shapes."""
    from paddle_tpu.inference.speculative import DraftModelDrafter

    m = _draft_model(seed=1)
    shapes = []
    orig = m.forward
    m.forward = lambda x: (shapes.append(tuple(x.shape)), orig(x))[1]
    try:
        d = DraftModelDrafter(m, window=16)
        rng = np.random.RandomState(2)
        hists = [rng.randint(0, 97, n).tolist() for n in (30, 5, 11)]
        d.propose_batch(hists, [3, 3, 3])
    finally:
        m.forward = orig
    assert shapes, "no draft forward ran"
    for r, w in shapes:
        assert r & (r - 1) == 0 and w & (w - 1) == 0, (r, w)
        assert w <= 16


def test_engine_draft_batch_bit_parity(model, monkeypatch):
    """Speculative decode with batched drafting on vs off: identical
    greedy outputs, fewer draft forwards, and the env knob
    (PADDLE_SPEC_DRAFT_BATCH=0) restores the per-sequence path."""
    rng = np.random.RandomState(6)
    prompts = [rng.randint(0, 128, (1, n)).astype(np.int64)
               for n in (19, 9)]

    def run(batched):
        eng = ContinuousServingEngine(
            model, max_batch_size=2, max_len=64, token_budget=16,
            prefill_chunk_tokens=16, spec_decode=True, spec_k=3,
            draft_model=model, draft_batch=batched)
        out = _drive(eng, prompts, 6)
        assert eng.spec_drafted_tokens > 0
        return out, eng

    got_on, eng_on = run(True)
    got_off, eng_off = run(False)
    for a, b in zip(got_on, got_off):
        np.testing.assert_array_equal(a, b)
    assert eng_on.spec_draft_ticks > 0
    # batched: at most spec_k forwards per tick regardless of rows; the
    # per-sequence path pays forwards ~= drafted tokens
    assert eng_on.spec_draft_forwards <= eng_off.spec_draft_forwards
    assert eng_on.spec_draft_forwards <= eng_on.spec_draft_ticks * 3
    monkeypatch.setenv("PADDLE_SPEC_DRAFT_BATCH", "0")
    eng = ContinuousServingEngine(model, spec_decode=True, spec_k=3,
                                  draft_model=model)
    assert eng.draft_batch is False
    monkeypatch.setenv("PADDLE_SPEC_DRAFT_BATCH", "1")
    assert ContinuousServingEngine(model, spec_decode=True, spec_k=3,
                                   draft_model=model).draft_batch is True
