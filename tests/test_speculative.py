"""Speculative decoding (ISSUE 10): drafter tiers, the ragged verify
path, SlotPagedKVCache.rollback lifecycle, seeded per-request sampling,
and the acceptance bar — greedy speculative outputs bit-identical to
plain greedy on a mixed workload (shared prefixes, staggered arrivals, a
cancellation, a fleet disagg handoff) with measured acceptance > 0 and
fewer target-model forwards than tokens generated."""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.fleet.elastic.tcp_kv import MemKVStore
from paddle_tpu.inference import (ContinuousServingEngine, ServingRouter,
                                  NGramDrafter, DraftModelDrafter,
                                  make_drafter)
from paddle_tpu.models import LlamaForCausalLM, llama_tiny
from paddle_tpu.models.generation import SlotPagedKVCache


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    return LlamaForCausalLM(llama_tiny(num_hidden_layers=2,
                                       max_position_embeddings=256))


def _oracle(model, p, n):
    return np.asarray(model.generate(paddle.to_tensor(p),
                                     max_new_tokens=n)._data)


class _WrongDrafter:
    """Adversarial drafter: always proposes tokens the target model will
    reject (token+1 mod vocab of whatever greedy would say is wrong by
    construction only probabilistically — so propose a constant garbage
    run instead; greedy acceptance must reject and roll back, and the
    output must not change)."""

    def propose(self, history, k):
        return [int(history[-1]) for _ in range(int(k))] if k > 0 else []


# ---------------------------------------------------------------------------
# drafter unit tier
# ---------------------------------------------------------------------------

def test_ngram_drafter_prompt_lookup():
    d = NGramDrafter(max_ngram=3)
    #          0  1  2  3  4  5  6  7  8
    hist = [5, 6, 7, 9, 1, 5, 6, 7]      # trailing [5,6,7] recurs at 0..2
    assert d.propose(hist, 3) == [9, 1, 5]
    assert d.propose(hist, 1) == [9]
    # no earlier occurrence of any trailing n-gram -> empty proposal
    assert d.propose([1, 2, 3, 4], 3) == []
    assert d.propose([7], 3) == []
    assert d.propose(hist, 0) == []


def test_ngram_drafter_backoff_and_recency():
    d = NGramDrafter(max_ngram=3)
    # trailing 3-gram unique, but trailing 1-gram [2] recurs twice: the
    # MOST RECENT earlier occurrence (index 4) supplies the continuation
    hist = [2, 9, 8, 7, 2, 3, 1, 2]
    assert d.propose(hist, 2) == [3, 1]


def test_draft_model_drafter_matches_target_greedy(model):
    rng = np.random.RandomState(0)
    p = rng.randint(0, 128, 12).astype(np.int64)
    d = DraftModelDrafter(model, window=64)
    drafts = d.propose(p, 3)
    want = _oracle(model, p[None], 3)[0, -3:]
    np.testing.assert_array_equal(np.asarray(drafts), want)


def test_make_drafter_factory(model, monkeypatch):
    assert isinstance(make_drafter(), NGramDrafter)
    assert isinstance(make_drafter(draft_model=model), DraftModelDrafter)
    monkeypatch.setenv("PADDLE_SPEC_NGRAM", "5")
    assert make_drafter("ngram").max_ngram == 5
    with pytest.raises(ValueError):
        make_drafter("model")                # no draft model given
    with pytest.raises(ValueError):
        make_drafter("warp")


# ---------------------------------------------------------------------------
# rollback lifecycle: refcounts, COW-shared pages, registered pages
# ---------------------------------------------------------------------------

def test_rollback_frees_private_pages():
    c = SlotPagedKVCache(2, page_size=4, max_len=32)
    c._ensure_blocks(0, 10)                  # 3 blocks
    c.lens[0] = 10
    free0 = c.free_page_count
    assert c.rollback(0, 5) == 5             # keep 5 tokens -> 2 blocks
    assert int(c.lens[0]) == 5
    assert int(c._n_blocks[0]) == 2
    assert c.free_page_count == free0 + 1    # block 2 went back
    assert c._tables[0, 2] == 0
    assert c.rollbacks == 1 and c.tokens_rolled_back == 5
    # zero/negative is a no-op; beyond the context raises
    assert c.rollback(0, 0) == 0
    with pytest.raises(ValueError):
        c.rollback(0, 6)


def test_rollback_keeps_cow_shared_page():
    c = SlotPagedKVCache(2, page_size=4, max_len=32)
    c._ensure_blocks(0, 8)                   # slot 0 owns 2 pages
    c.lens[0] = 8
    shared = int(c._tables[0, 1])
    c._tables[1, 0] = shared                 # slot 1 aliases block 1
    c._ref[shared] += 1
    c._n_blocks[1] = 1
    c.lens[1] = 4
    c.rollback(0, 5)                         # truncates past the share
    assert c._ref[shared] == 1               # slot 1's ref survives
    assert int(c._tables[1, 0]) == shared
    assert shared not in c._free


def test_rollback_keeps_prefix_registered_page():
    c = SlotPagedKVCache(2, page_size=4, max_len=32)
    c._ensure_blocks(0, 8)
    c.lens[0] = 8
    page = int(c._tables[0, 1])
    digest = b"\x01" * 20
    c._index[digest] = page                  # register block 1
    c._page_digest[page] = digest
    c._ref[page] += 1                        # the index's own ref
    free0 = c.free_page_count
    c.rollback(0, 8)                         # truncate the whole slot
    # the registered page stays alive under the index's ref...
    assert c._ref[page] == 1
    assert c._index[digest] == page
    assert c.free_page_count == free0 + 1    # only block 0 was freed
    # ...and remains evictable through the normal LRU path
    assert c._evict_lru()
    assert page in c._free


# ---------------------------------------------------------------------------
# engine: spec requires ragged; env knobs
# ---------------------------------------------------------------------------

def test_spec_requires_ragged_scheduler(model):
    with pytest.raises(ValueError):
        ContinuousServingEngine(model, spec_decode=True,
                                enable_ragged=False)


def test_spec_env_knobs(model, monkeypatch):
    assert ContinuousServingEngine(model).enable_spec is False
    monkeypatch.setenv("PADDLE_SPEC_DECODE", "1")
    monkeypatch.setenv("PADDLE_SPEC_K", "2")
    eng = ContinuousServingEngine(model)
    assert eng.enable_spec is True and eng.spec_k == 2
    assert isinstance(eng._drafter, NGramDrafter)
    monkeypatch.setenv("PADDLE_SPEC_DRAFTER", "model")
    with pytest.raises(ValueError):          # model tier needs a model
        ContinuousServingEngine(model)
    eng = ContinuousServingEngine(model, draft_model=model)
    assert isinstance(eng._drafter, DraftModelDrafter)


# ---------------------------------------------------------------------------
# acceptance: mixed workload bit-parity + fewer forwards than tokens
# ---------------------------------------------------------------------------

def _run_workload(model, prompts, new, **engine_kw):
    eng = ContinuousServingEngine(
        model, max_batch_size=4, max_len=96, page_size=16,
        prefill_chunk_tokens=24, token_budget=32, **engine_kw)
    results = [None] * len(prompts)
    with eng:
        results[0] = np.asarray(eng.generate(
            prompts[0], max_new_tokens=new, timeout=300).numpy())

        def call(i):
            time.sleep(0.01 * i)             # staggered arrivals
            results[i] = np.asarray(eng.generate(
                prompts[i], max_new_tokens=new, timeout=300).numpy())

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(1, len(prompts))]
        for t in threads:
            t.start()
        # one request that gives up while the engine is busy
        with pytest.raises(TimeoutError):
            eng.generate(prompts[0], max_new_tokens=30, timeout=0.001)
        for t in threads:
            t.join()
        deadline = time.time() + 60
        while eng.cancelled_rows < 1 and time.time() < deadline:
            time.sleep(0.01)
    assert eng.cancelled_rows >= 1
    return results, eng


def test_spec_mixed_workload_bit_identical_and_fewer_forwards(model):
    """The PR's acceptance bar: 8 requests with shared prefixes,
    staggered arrivals and a timeout cancellation — greedy outputs with
    speculative decoding ON (self-draft tier-2 drafter, acceptance ~1)
    bit-identical to PADDLE_SPEC_DECODE=0 plain greedy, with measured
    acceptance > 0 and fewer target-model forwards than tokens
    generated, asserted via the engine/telemetry counters."""
    from paddle_tpu.profiler import metrics

    rng = np.random.RandomState(0)
    shared = rng.randint(0, 128, 48)
    specs = [3, 9, 5, 14, 7, 4, 11, 6]
    prompts = [np.concatenate([shared, rng.randint(0, 128, t)])
               .astype(np.int64)[None] for t in specs]
    new = 8

    got_off, eng_off = _run_workload(model, prompts, new)
    got_on, eng_on = _run_workload(model, prompts, new, spec_decode=True,
                                   spec_k=3, draft_model=model)
    for a, b in zip(got_on, got_off):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(got_on[0], _oracle(model, prompts[0],
                                                     new))
    # acceptance rate > 0, and the accepted drafts shrank the number of
    # target forwards below one-per-token
    tokens = len(prompts) * new
    assert eng_on.spec_drafted_tokens > 0
    assert eng_on.spec_accepted_tokens > 0
    rate = eng_on.spec_accepted_tokens / eng_on.spec_drafted_tokens
    assert rate > 0.9                        # self-draft: near-total
    assert eng_on.ragged_steps < eng_off.ragged_steps
    assert eng_on.decode_steps < tokens      # forwards < tokens generated
    assert eng_on.decode_steps < eng_off.decode_steps
    # telemetry counters carry the same story
    snap = metrics()["paddle_spec_tokens_total"]["series"]
    assert snap.get("drafted", 0) >= eng_on.spec_drafted_tokens
    assert snap.get("accepted", 0) >= eng_on.spec_accepted_tokens
    # prefix cache still worked under spec decode
    assert eng_on._cache.prefix_hits > 0


def test_spec_rejections_roll_back_and_stay_correct(model):
    """A drafter that is always wrong costs speed, never text: every
    draft is rejected, every rejection rolls back, outputs match."""
    rng = np.random.RandomState(1)
    p = rng.randint(0, 128, (1, 20)).astype(np.int64)
    want = _oracle(model, p, 6)
    eng = ContinuousServingEngine(model, max_batch_size=2, max_len=64,
                                  token_budget=16, spec_decode=True,
                                  spec_k=3, drafter=_WrongDrafter())
    with eng:
        got = np.asarray(eng.generate(p, max_new_tokens=6,
                                      timeout=300).numpy())
    np.testing.assert_array_equal(got, want)
    assert eng.spec_drafted_tokens > 0
    assert eng._cache.rollbacks > 0
    assert eng._cache.tokens_rolled_back >= eng.spec_drafted_tokens \
        - eng.spec_accepted_tokens


def test_spec_ngram_drafter_bit_identical(model):
    """The model-free tier: whatever the n-gram drafter proposes (hit or
    miss), greedy output is bit-identical to spec-off. The prompt is a
    permutation of the whole vocab, so EVERY generated token has a
    1-gram match and the drafter provably fires."""
    rng = np.random.RandomState(2)
    p = rng.permutation(128).astype(np.int64)[None]
    want = _oracle(model, p, 6)
    eng = ContinuousServingEngine(model, max_batch_size=2, max_len=160,
                                  token_budget=32, spec_decode=True,
                                  spec_k=4)
    assert isinstance(eng._drafter, NGramDrafter)
    with eng:
        got = np.asarray(eng.generate(p, max_new_tokens=6,
                                      timeout=300).numpy())
    np.testing.assert_array_equal(got, want)
    assert eng.spec_drafted_tokens > 0       # full-vocab prompt: 1-gram hit


# ---------------------------------------------------------------------------
# seeded per-request sampling (satellite): reproducible + spec-exact
# ---------------------------------------------------------------------------

def test_seeded_sampling_reproducible(model):
    rng = np.random.RandomState(3)
    p = rng.randint(0, 128, (1, 16)).astype(np.int64)

    def run(seed, **kw):
        eng = ContinuousServingEngine(model, max_batch_size=2, max_len=64,
                                      token_budget=16, **kw)
        with eng:
            return np.asarray(eng.generate(
                p, max_new_tokens=8, do_sample=True, temperature=1.3,
                seed=seed, timeout=300).numpy())

    a, b = run(7), run(7)
    np.testing.assert_array_equal(a, b)      # same seed -> same text
    assert not np.array_equal(a, run(8))     # different seed diverges
    # legacy scheduler derives the identical per-token keys
    np.testing.assert_array_equal(a, run(7, enable_ragged=False))


def test_seeded_sampling_spec_verification_exact(model):
    """Sampled speculative decode with a seed is exact: the per-token
    key depends only on the token INDEX, so verification reproduces the
    very draw plain sampled decode would have made."""
    rng = np.random.RandomState(4)
    p = rng.randint(0, 128, (1, 16)).astype(np.int64)

    def run(**kw):
        eng = ContinuousServingEngine(model, max_batch_size=2, max_len=64,
                                      token_budget=16, **kw)
        with eng:
            out = np.asarray(eng.generate(
                p, max_new_tokens=8, do_sample=True, temperature=1.3,
                seed=11, timeout=300).numpy())
        return out, eng

    off, _ = run()
    on, eng = run(spec_decode=True, spec_k=3, draft_model=model)
    np.testing.assert_array_equal(on, off)
    assert eng.spec_drafted_tokens > 0


def test_generation_mixin_seed(model):
    rng = np.random.RandomState(5)
    p = paddle.to_tensor(rng.randint(0, 128, (2, 10)).astype(np.int64))
    a = np.asarray(model.generate(p, max_new_tokens=6, do_sample=True,
                                  seed=3)._data)
    b = np.asarray(model.generate(p, max_new_tokens=6, do_sample=True,
                                  seed=3)._data)
    np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# fleet composition: disagg handoff with spec decode on
# ---------------------------------------------------------------------------

def test_spec_fleet_disagg_handoff_parity(model):
    """Speculative decoding composes with the disaggregated fleet: the
    prefill replica never decodes (max_new=1 leaves no draft room), the
    decode replica speculates over imported pages, and outputs stay
    bit-identical to the plain single-engine oracle."""
    rng = np.random.RandomState(6)
    shared = rng.randint(0, 128, 32)
    prompts = [np.concatenate([shared, rng.randint(0, 128, t)])
               .astype(np.int64)[None] for t in (4, 7, 5)]
    want = [_oracle(model, p, 4) for p in prompts]
    router = ServingRouter(
        model, num_replicas=2, disagg=True, store=MemKVStore(),
        heartbeat_ttl=600.0,
        engine_kwargs=dict(max_batch_size=2, max_len=96,
                           spec_decode=True, spec_k=3,
                           draft_model=model))
    with router:
        results = [np.asarray(router.generate(
            p, max_new_tokens=4, timeout=600).numpy()) for p in prompts]
        pre, dec = router.replicas
        assert pre.engine.decode_steps == 0
        assert dec.engine._cache.pages_imported > 0
        assert dec.engine.spec_accepted_tokens > 0
    for g, w in zip(results, want):
        np.testing.assert_array_equal(g, w)
