"""Sparse + quantization tests (reference: paddle.sparse /
paddle.quantization — SURVEY.md §2.2)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import sparse
from paddle_tpu.quantization import (
    QuantConfig, QAT, PTQ, FakeQuanterWithAbsMaxObserver, AbsmaxObserver,
    convert, fake_quant,
)


# ---------------------------------------------------------------------------
# sparse
# ---------------------------------------------------------------------------

def test_coo_roundtrip():
    idx = [[0, 1, 2], [1, 2, 0]]
    vals = [1.0, 2.0, 3.0]
    st = sparse.sparse_coo_tensor(idx, vals, shape=[3, 3])
    assert st.is_sparse_coo() and st.nnz == 3
    dense = st.to_dense().numpy()
    ref = np.zeros((3, 3), np.float32)
    ref[0, 1], ref[1, 2], ref[2, 0] = 1, 2, 3
    np.testing.assert_allclose(dense, ref)
    np.testing.assert_allclose(st.values().numpy(), vals)
    assert st.indices().shape == [2, 3]


def test_csr_roundtrip_and_convert():
    st = sparse.sparse_csr_tensor([0, 1, 2, 3], [1, 2, 0], [1.0, 2.0, 3.0],
                                  shape=[3, 3])
    assert st.is_sparse_csr() and st.nnz == 3
    coo = st.to_sparse_coo()
    np.testing.assert_allclose(coo.to_dense().numpy(), st.to_dense().numpy())


def test_sparse_add_multiply_relu():
    a = sparse.sparse_coo_tensor([[0, 1], [0, 1]], [1.0, -2.0], [2, 2])
    b = sparse.sparse_coo_tensor([[0, 1], [0, 0]], [5.0, 1.0], [2, 2])
    s = sparse.add(a, b)
    np.testing.assert_allclose(s.to_dense().numpy(),
                               [[6.0, 0.0], [1.0, -2.0]])
    r = sparse.relu(a)
    np.testing.assert_allclose(r.to_dense().numpy(), [[1.0, 0.0], [0.0, 0.0]])


def test_sparse_matmul_grad():
    a = sparse.sparse_coo_tensor([[0, 0, 1], [0, 1, 1]], [1.0, 2.0, 3.0],
                                 [2, 2])
    x = paddle.to_tensor(np.eye(2, dtype=np.float32), stop_gradient=False)
    out = sparse.matmul(a, x)
    np.testing.assert_allclose(out.numpy(), [[1.0, 2.0], [0.0, 3.0]])
    out.sum().backward()
    assert x.grad is not None
    # d(sum(A@X))/dX = A^T @ ones
    np.testing.assert_allclose(x.grad.numpy(), [[1.0, 1.0], [5.0, 5.0]])


def test_masked_matmul():
    x = paddle.to_tensor(np.arange(4, dtype=np.float32).reshape(2, 2))
    y = paddle.to_tensor(np.ones((2, 2), np.float32))
    mask = sparse.sparse_coo_tensor([[0, 1], [1, 0]], [1.0, 1.0], [2, 2])
    out = sparse.masked_matmul(x, y, mask)
    dense = out.to_dense().numpy()
    full = x.numpy() @ y.numpy()
    assert dense[0, 1] == full[0, 1] and dense[1, 0] == full[1, 0]
    assert dense[0, 0] == 0 and dense[1, 1] == 0


# ---------------------------------------------------------------------------
# quantization
# ---------------------------------------------------------------------------

def test_fake_quant_ste():
    x = paddle.to_tensor(np.linspace(-2, 2, 9, dtype=np.float32),
                         stop_gradient=False)
    scale = paddle.to_tensor(np.float32(1.0))
    out = fake_quant(x, scale, 8)
    # quantized to 1/127 grid within [-1, 1], clipped outside
    assert abs(float(out.numpy().max()) - 1.0) < 1e-6
    out.sum().backward()
    g = x.grad.numpy()
    inside = np.abs(x.numpy()) <= 1.0
    np.testing.assert_allclose(g[inside], 1.0)
    np.testing.assert_allclose(g[~inside], 0.0)


def test_qat_quantize_and_train():
    paddle.seed(0)
    model = paddle.nn.Sequential(
        paddle.nn.Linear(8, 16), paddle.nn.ReLU(), paddle.nn.Linear(16, 4))
    q = QuantConfig(activation=FakeQuanterWithAbsMaxObserver(),
                    weight=FakeQuanterWithAbsMaxObserver())
    qmodel = QAT(q).quantize(model)
    from paddle_tpu.quantization import QuantedLinear
    assert isinstance(qmodel._sub_layers["0"], QuantedLinear)
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=qmodel.parameters())
    x = paddle.randn([4, 8])
    losses = []
    for _ in range(5):
        loss = (qmodel(x) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_convert_int8():
    paddle.seed(1)
    model = paddle.nn.Sequential(paddle.nn.Linear(4, 4))
    q = QuantConfig(activation=None, weight=AbsmaxObserver())
    qmodel = PTQ(q).quantize(model)
    ref_w = qmodel._sub_layers["0"].inner.weight.numpy().copy()
    convert(qmodel)
    lin = qmodel._sub_layers["0"]
    assert lin.int8_weight.dtype == np.int8
    # per-output-channel dequant reconstructs within one quantum per channel
    deq = lin._w_int8.astype(np.float32) * lin._w_scale[None, :]
    assert (np.abs(deq - ref_w) <= lin._w_scale[None, :] + 1e-6).all()


def test_int8_matmul_kernel():
    from paddle_tpu.ops.pallas import int8_matmul, quantize_weight
    import jax.numpy as jnp
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(8, 192)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(192, 136)), jnp.float32)
    q, scale = quantize_weight(w)
    out = int8_matmul(x, q, scale, block_m=8, block_n=128, block_k=128,
                      interpret=True)
    ref = x @ (q.astype(jnp.float32) * scale[None, :])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)
    # quantization error itself bounded by one quantum per element pair
    full = x @ w
    err = np.abs(np.asarray(out) - np.asarray(full))
    bound = (np.abs(np.asarray(x)) @ np.ones_like(np.asarray(w))) * \
        np.asarray(scale)[None, :]
    assert (err <= bound + 1e-4).all()


def test_converted_linear_uses_int8_path():
    paddle.seed(2)
    model = paddle.nn.Sequential(paddle.nn.Linear(16, 8))
    qmodel = PTQ(QuantConfig(activation=None,
                             weight=AbsmaxObserver())).quantize(model)
    convert(qmodel)
    lin = qmodel._sub_layers["0"]
    assert lin._converted
    qmodel.eval()
    x = paddle.randn([4, 16])
    out = qmodel(x)                     # int8 pallas path (interpret on CPU)
    ref = x.numpy() @ (lin._w_int8.astype(np.float32)
                       * lin._w_scale[None, :]) + lin.inner.bias.numpy()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-4)
