"""Native C++ shm queue + DataLoader shared-memory transport tests
(reference: blocking_queue.h / shared-mem DataLoader blobs — SURVEY.md §3.5)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import DataLoader, Dataset
from paddle_tpu.io import native


pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native shm queue unavailable")


def test_shm_queue_roundtrip_and_regrow():
    q = native.ShmQueue(f"t_rt_{os.getpid()}", create=True)
    q.put((0, np.arange(5), None))
    bidx, arr, err = q.get(timeout=2)
    assert bidx == 0 and arr.sum() == 10 and err is None
    big = np.random.default_rng(0).normal(size=(1 << 20,))  # > 1MB recv buf
    q.put((1, big, None))
    _, out, _ = q.get(timeout=2)
    np.testing.assert_array_equal(out, big)
    assert q.stats() == {"pushed": 2, "popped": 2}
    q.close()


def test_shm_queue_timeout():
    q = native.ShmQueue(f"t_to_{os.getpid()}", create=True)
    with pytest.raises(TimeoutError):
        q.get(timeout=0.1)
    q.close()


def test_shm_queue_oversize_message_chunks_across_slots():
    # a blob far bigger than one slot must round-trip via chunked frames,
    # not raise (the round-4 goodput crash: 78 MB batch vs 64 MiB slot)
    q = native.ShmQueue(f"t_of_{os.getpid()}", create=True, slots=4,
                        slot_bytes=1024)
    big = np.random.default_rng(1).normal(size=(10_000,))  # ~80 KB pickled

    import threading
    err = []

    def producer():
        try:
            q.put(big)
        except Exception as e:      # pragma: no cover
            err.append(e)

    t = threading.Thread(target=producer)
    t.start()                       # blocks on the 4-slot ring until drained
    out = q.get(timeout=10)
    t.join(timeout=10)
    assert not err
    np.testing.assert_array_equal(out, big)
    q.close()


def test_shm_queue_interleaved_chunked_producers():
    # two producer processes push multi-chunk messages concurrently on a
    # tiny ring; the consumer must reassemble both despite interleaving
    import multiprocessing as mp

    name = f"t_il_{os.getpid()}"
    q = native.ShmQueue(name, create=True, slots=3, slot_bytes=2048)

    def producer(tag):
        wq = native.ShmQueue(name)
        wq.put((tag, np.full(2_000, tag, np.float64)))
        wq.close()

    ctx = mp.get_context("fork" if "fork" in mp.get_all_start_methods()
                         else "spawn")
    procs = [ctx.Process(target=producer, args=(t,)) for t in (1, 2)]
    for p in procs:
        p.start()
    got = {}
    for _ in range(2):
        tag, arr = q.get(timeout=30)
        got[tag] = arr
    for p in procs:
        p.join(timeout=10)
    assert set(got) == {1, 2}
    for tag, arr in got.items():
        np.testing.assert_array_equal(arr, np.full(2_000, tag, np.float64))
    q.close()


def test_shm_queue_capacity_blocks_then_drains():
    q = native.ShmQueue(f"t_cap_{os.getpid()}", create=True, slots=2,
                        slot_bytes=4096)
    q.put("a")
    q.put("b")
    with pytest.raises(TimeoutError):
        q.put("c", timeout=0.1)      # full
    assert q.get(timeout=1) == "a"   # FIFO order
    q.put("c")
    assert q.get(timeout=1) == "b"
    assert q.get(timeout=1) == "c"
    q.close()


class _SquareDs(Dataset):
    def __len__(self):
        return 32

    def __getitem__(self, i):
        return np.full((4, 4), i, np.float32), np.int64(i)


def test_dataloader_shm_transport_matches_single_process():
    ds = _SquareDs()
    ref = [(x.numpy().copy(), y.numpy().copy())
           for x, y in DataLoader(ds, batch_size=4, num_workers=0,
                                  shuffle=False)]
    loader = DataLoader(ds, batch_size=4, num_workers=2, shuffle=False,
                        use_shared_memory=True)
    it = iter(loader)
    # confirm the native transport is actually in use (the loader exposes
    # its live inner iterator; unwrap the prefetch wrapper if present)
    inner = loader._active_inner
    inner = getattr(inner, "inner", inner)
    assert inner._shm is not None
    got = [(x.numpy(), y.numpy()) for x, y in it]
    assert len(got) == len(ref)
    for (x, y), (rx, ry) in zip(got, ref):
        np.testing.assert_array_equal(x, rx)
        np.testing.assert_array_equal(y, ry)


class _FailingDs(Dataset):
    def __len__(self):
        return 8

    def __getitem__(self, i):
        if i == 5:
            raise ValueError("boom at 5")
        return np.zeros(2, np.float32)


def test_dataloader_shm_propagates_worker_error():
    loader = DataLoader(_FailingDs(), batch_size=2, num_workers=2,
                        use_shared_memory=True)
    with pytest.raises(RuntimeError, match="boom at 5"):
        for _ in loader:
            pass


class _HugeDs(Dataset):
    """One sample is ~40 MB, so a batch of 2 pickles past the 64 MiB slot —
    the exact shape of the round-4 PP-YOLOE goodput crash."""

    def __len__(self):
        return 4

    def __getitem__(self, i):
        return (np.full((40, 512, 512), np.float32(i), np.float32),
                np.int64(i))


def test_dataloader_shm_batch_larger_than_slot():
    loader = DataLoader(_HugeDs(), batch_size=2, num_workers=1,
                        shuffle=False, use_shared_memory=True)
    seen = []
    for x, y in loader:
        assert x.shape == [2, 40, 512, 512]
        seen.extend(int(v) for v in y.numpy())
        # spot-check content integrity across the chunk boundary
        xn = x.numpy()
        for j, v in enumerate(y.numpy()):
            assert float(xn[j, 0, 0, 0]) == float(v)
            assert float(xn[j, -1, -1, -1]) == float(v)
    assert seen == [0, 1, 2, 3]
