"""Profiler facade tests (reference: paddle.profiler — SURVEY.md §5.1)."""
import json
import os

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.profiler import (
    Profiler, ProfilerTarget, ProfilerState, make_scheduler,
    export_chrome_tracing, RecordEvent, benchmark,
)


def test_make_scheduler_windows():
    sch = make_scheduler(closed=1, ready=1, record=2, repeat=1, skip_first=1)
    states = [sch(i) for i in range(7)]
    assert states[0] == ProfilerState.CLOSED          # skip_first
    assert states[1] == ProfilerState.CLOSED
    assert states[2] == ProfilerState.READY
    assert states[3] == ProfilerState.RECORD
    assert states[4] == ProfilerState.RECORD_AND_RETURN
    assert states[5] == ProfilerState.CLOSED          # repeat exhausted
    assert states[6] == ProfilerState.CLOSED


def test_profiler_records_ops_and_steps(tmp_path):
    traces = str(tmp_path / "traces")
    with Profiler(targets=[ProfilerTarget.CPU],
                  on_trace_ready=export_chrome_tracing(traces)) as p:
        x = paddle.randn([32, 32])
        for _ in range(3):
            y = (x @ x).sum()
            with RecordEvent("custom_region"):
                _ = y + 1
            p.step()
    assert p._op_stats, "no ops recorded"
    ops = dict(p._op_stats)
    assert any("matmul" in k for k in ops), ops.keys()
    assert "user::custom_region" in ops
    assert len(p._step_times) == 3
    out = p.summary()
    assert "matmul" in out
    # chrome trace written and valid json
    files = os.listdir(traces)
    assert files
    with open(os.path.join(traces, files[0])) as f:
        data = json.load(f)
    assert data["traceEvents"]


def test_profiler_scheduler_gates_recording():
    sch = make_scheduler(closed=1, ready=0, record=1, repeat=2)
    with Profiler(targets=[ProfilerTarget.CPU], scheduler=sch) as p:
        x = paddle.randn([8])
        for i in range(4):
            _ = x + i          # recorded only during RECORD windows
            p.step()
    total_calls = sum(c for c, _ in p._op_stats.values())
    assert 0 < total_calls < 8   # strictly fewer than if always recording


def test_benchmark_ips():
    b = benchmark()
    b.begin()
    for _ in range(5):
        b.step(num_samples=10)
    b.end()
    assert b.ips() > 0
    assert "ips" in b.step_info()
