"""Round-3 op tranche: fft hermitian family, sparse op breadth, and the
new dense ops' non-OpCase checks (VERDICT.md round-2 item 7)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import fft as pfft
from paddle_tpu import sparse as psp


RNG = np.random.RandomState(3)


def t(x):
    return paddle.to_tensor(x)


# ---------------------------------------------------------------------------
# fft: every transform round-trips / matches numpy
# ---------------------------------------------------------------------------

def test_fft_ifft_roundtrip_and_numpy():
    x = RNG.randn(4, 8).astype(np.float32)
    got = pfft.fft(t(x)).numpy()
    np.testing.assert_allclose(got, np.fft.fft(x), rtol=1e-4, atol=1e-4)
    back = pfft.ifft(t(np.asarray(got))).numpy()
    np.testing.assert_allclose(back.real, x, rtol=1e-4, atol=1e-4)


def test_fftn_ifftn_rfftn_irfftn():
    x = RNG.randn(3, 4, 6).astype(np.float32)
    np.testing.assert_allclose(pfft.fftn(t(x)).numpy(), np.fft.fftn(x),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(
        pfft.ifftn(t(np.fft.fftn(x))).numpy().real, x,
        rtol=1e-4, atol=1e-4)
    r = pfft.rfftn(t(x)).numpy()
    np.testing.assert_allclose(r, np.fft.rfftn(x), rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(pfft.irfftn(t(r), s=x.shape[-1:]).numpy()
                               if False else
                               pfft.irfftn(t(np.asarray(r))).numpy(),
                               x, rtol=1e-4, atol=1e-4)


def test_rfft2_irfft2_and_freqs():
    x = RNG.randn(4, 6).astype(np.float32)
    r = pfft.rfft2(t(x)).numpy()
    np.testing.assert_allclose(r, np.fft.rfft2(x), rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(pfft.irfft2(t(np.asarray(r))).numpy(), x,
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(pfft.rfftfreq(8, 0.5).numpy(),
                               np.fft.rfftfreq(8, 0.5), rtol=1e-6)
    y = RNG.randn(8).astype(np.float32)
    np.testing.assert_allclose(
        pfft.ifftshift(pfft.fftshift(t(y))).numpy(), y, rtol=1e-6)


def test_hfft_family_matches_numpy_1d():
    # hermitian-symmetric input -> hfft output is real
    z = (RNG.randn(5) + 1j * RNG.randn(5)).astype(np.complex64)
    got = pfft.hfft(t(z)).numpy()
    np.testing.assert_allclose(got, np.fft.hfft(z), rtol=1e-3, atol=1e-3)
    x = RNG.randn(8).astype(np.float32)
    np.testing.assert_allclose(pfft.ihfft(t(x)).numpy(), np.fft.ihfft(x),
                               rtol=1e-4, atol=1e-4)


def test_hfft2_ihfft2_roundtrip():
    x = RNG.randn(4, 10).astype(np.float32)
    spec = pfft.ihfft2(t(x)).numpy()         # [4, 6] hermitian half-spec
    back = pfft.hfft2(t(np.asarray(spec)), s=(4, 10)).numpy()
    assert back.dtype == np.float32          # real output
    np.testing.assert_allclose(back, x, rtol=1e-3, atol=1e-3)


def test_hfftn_ihfftn_roundtrip():
    x = RNG.randn(3, 4, 8).astype(np.float32)
    spec = pfft.ihfftn(t(x)).numpy()
    back = pfft.hfftn(t(np.asarray(spec)), s=(3, 4, 8)).numpy()
    np.testing.assert_allclose(back, x, rtol=1e-3, atol=1e-3)


# ---------------------------------------------------------------------------
# sparse op breadth
# ---------------------------------------------------------------------------

def _coo(dense):
    idx = np.argwhere(dense != 0)
    vals = dense[dense != 0]
    return psp.sparse_coo_tensor(idx.T, vals, shape=dense.shape)


@pytest.fixture
def sp_pair():
    d = RNG.randn(4, 5).astype(np.float32)
    d[RNG.rand(4, 5) < 0.5] = 0.0
    return d, _coo(d)


UNARY_SPARSE = ["sin", "tan", "asin", "atan", "sinh", "tanh", "asinh",
                "sqrt", "square", "abs", "neg", "expm1", "log1p",
                "rad2deg", "deg2rad"]


def test_sparse_unary_matrix(sp_pair):
    d, s = sp_pair
    d_abs = np.abs(d) * 0.5            # safe domain for sqrt/asin/atanh
    s_abs = _coo(d_abs)
    np_ref = {"sin": np.sin, "tan": np.tan, "asin": np.arcsin,
              "atan": np.arctan, "sinh": np.sinh, "tanh": np.tanh,
              "asinh": np.arcsinh, "sqrt": np.sqrt, "square": np.square,
              "abs": np.abs, "neg": np.negative, "expm1": np.expm1,
              "log1p": np.log1p, "rad2deg": np.rad2deg,
              "deg2rad": np.deg2rad}
    for name in UNARY_SPARSE:
        out = getattr(psp, name)(s_abs)
        ref = np.where(d_abs != 0, np_ref[name](d_abs), 0.0)
        np.testing.assert_allclose(np.asarray(out.to_dense().numpy()), ref,
                                   rtol=1e-4, atol=1e-5, err_msg=name)
    # atanh separately (domain |x|<1 ok with 0.5*|d|), isnan, pow, cast
    out = psp.atanh(s_abs)
    np.testing.assert_allclose(np.asarray(out.to_dense().numpy()),
                               np.where(d_abs != 0, np.arctanh(d_abs), 0),
                               rtol=1e-4, atol=1e-5)
    # bool sparse: BCOO.todense needs an additive dtype, so check values
    assert not np.asarray(psp.isnan(s_abs).values().numpy()).any()
    out = psp.pow(s_abs, 2.0)
    np.testing.assert_allclose(np.asarray(out.to_dense().numpy()),
                               d_abs ** 2, rtol=1e-4, atol=1e-5)
    c = psp.cast(s_abs, value_dtype="float16")   # x64 is disabled in jax
    assert c.dtype == np.float16


def test_sparse_binary_and_reductions(sp_pair):
    d, s = sp_pair
    d2 = RNG.randn(4, 5).astype(np.float32)
    d2[d == 0] = 0.0                    # same pattern
    s2 = _coo(d2) if (d2 != 0).any() else _coo(d)
    out = psp.subtract(s, s2)
    np.testing.assert_allclose(np.asarray(out.to_dense().numpy()), d - d2,
                               rtol=1e-5, atol=1e-6)
    dense_div = RNG.rand(4, 5).astype(np.float32) + 1.0
    out = psp.divide(s, t(dense_div))
    np.testing.assert_allclose(np.asarray(out.to_dense().numpy()),
                               np.where(d != 0, d / dense_div, 0.0),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(float(psp.sum(s).numpy()), d.sum(),
                               rtol=1e-4)
    out = psp.sum(s, axis=1)
    np.testing.assert_allclose(np.asarray(out.to_dense().numpy()),
                               d.sum(1), rtol=1e-4, atol=1e-5)


def test_sparse_structure_ops(sp_pair):
    d, s = sp_pair
    out = psp.transpose(s, [1, 0])
    np.testing.assert_allclose(np.asarray(out.to_dense().numpy()), d.T,
                               rtol=1e-6)
    out = psp.reshape(s, [5, 4])
    np.testing.assert_allclose(np.asarray(out.to_dense().numpy()),
                               d.reshape(5, 4), rtol=1e-6)
    assert psp.is_same_shape(s, s) and not psp.is_same_shape(
        s, psp.reshape(s, [5, 4]))
    assert psp.is_sparse(s) and not psp.is_sparse(t(d))
    co = psp.coalesce(s)
    np.testing.assert_allclose(np.asarray(co.to_dense().numpy()), d,
                               rtol=1e-6)
    dense_src = RNG.randn(4, 5).astype(np.float32)
    out = psp.mask_as(t(dense_src), s)
    np.testing.assert_allclose(np.asarray(out.to_dense().numpy()),
                               np.where(d != 0, dense_src, 0.0), rtol=1e-6)
    out = psp.slice(s, [0, 1], [1, 0], [3, 4])
    np.testing.assert_allclose(np.asarray(out.to_dense().numpy()),
                               d[1:3, 0:4], rtol=1e-6)


def test_sparse_mv_addmm(sp_pair):
    d, s = sp_pair
    v = RNG.randn(5).astype(np.float32)
    np.testing.assert_allclose(np.asarray(psp.mv(s, t(v)).numpy()), d @ v,
                               rtol=1e-4, atol=1e-4)
    x2 = RNG.randn(5, 3).astype(np.float32)
    base = RNG.randn(4, 3).astype(np.float32)
    out = psp.addmm(t(base), s, t(x2), beta=0.5, alpha=2.0)
    np.testing.assert_allclose(np.asarray(out.numpy()),
                               0.5 * base + 2.0 * (d @ x2),
                               rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# dense op extras that OpCase can't express
# ---------------------------------------------------------------------------

def test_polar_complex():
    r = np.abs(RNG.randn(3, 4)).astype(np.float32)
    th = RNG.randn(3, 4).astype(np.float32)
    out = np.asarray(paddle.polar(t(r), t(th)).numpy())
    np.testing.assert_allclose(out, r * np.exp(1j * th), rtol=1e-4,
                               atol=1e-5)


def test_svd_lowrank_reconstructs():
    a = np.random.RandomState(11).randn(8, 3).astype(np.float32)
    low = (a @ a.T).astype(np.float32)        # rank 3 PSD
    u, sval, v = paddle.linalg.svd_lowrank(t(low), q=3, niter=3)
    rec = np.asarray(u.numpy()) * np.asarray(sval.numpy()) \
        @ np.asarray(v.numpy()).T
    np.testing.assert_allclose(rec, low, rtol=1e-2, atol=1e-2)


def test_fill_diagonal_inplace():
    x = t(np.zeros((4, 4), np.float32))
    paddle.tensor.fill_diagonal_(x, 5.0) if hasattr(
        paddle.tensor, "fill_diagonal_") else paddle.fill_diagonal_(x, 5.0)
    np.testing.assert_allclose(np.asarray(x.numpy()),
                               np.eye(4, dtype=np.float32) * 5.0)
    y = t(np.zeros((3, 3), np.float32))
    paddle.fill_diagonal_tensor_(y, t(np.asarray([1., 2., 3.], np.float32)))
    np.testing.assert_allclose(np.asarray(y.numpy()),
                               np.diag([1., 2., 3.]).astype(np.float32))


def test_fill_diagonal_offset_and_hyper():
    x = t(np.zeros((4, 5), np.float32))
    paddle.fill_diagonal_(x, 2.0, offset=1)
    want = np.zeros((4, 5), np.float32)
    for i in range(4):
        want[i, i + 1] = 2.0
    np.testing.assert_allclose(np.asarray(x.numpy()), want)
    y = t(np.zeros((3, 3, 3), np.float32))
    paddle.fill_diagonal_(y, 7.0)
    got = np.asarray(y.numpy())
    assert got[0, 0, 0] == got[1, 1, 1] == got[2, 2, 2] == 7.0
    assert got.sum() == 21.0


def test_svd_lowrank_batched():
    a = np.random.RandomState(12).randn(2, 6, 3).astype(np.float32)
    low = np.einsum("bik,bjk->bij", a, a).astype(np.float32)
    u, s, v = paddle.linalg.svd_lowrank(t(low), q=3, niter=3)
    rec = np.einsum("bik,bk,bjk->bij", np.asarray(u.numpy()),
                    np.asarray(s.numpy()), np.asarray(v.numpy()))
    np.testing.assert_allclose(rec, low, rtol=1e-2, atol=1e-2)


def test_hfftn_s_without_axes_uses_last_axes():
    x = RNG.randn(2, 4, 8).astype(np.float32)
    spec = pfft.ihfftn(t(x), s=(4, 8), axes=(-2, -1)).numpy()
    back = pfft.hfftn(t(np.asarray(spec)), s=(4, 8)).numpy()  # axes=None
    np.testing.assert_allclose(back, x, rtol=1e-3, atol=1e-3)


def test_top_p_sampling_respects_nucleus():
    paddle.seed(0)
    probs = np.asarray([[0.6, 0.3, 0.05, 0.05]] * 4, np.float32)
    ps = np.full((4,), 0.7, np.float32)
    _, idx = paddle.top_p_sampling(t(probs), t(ps))
    # 0.6 alone reaches 0.6 < 0.7, so {0, 1} form the nucleus
    assert set(np.asarray(idx.numpy()).ravel()) <= {0, 1}


def test_fused_swiglu_matches_composition():
    from paddle_tpu.ops.fused import fused_swiglu
    import jax.numpy as jnp
    x = jnp.asarray(RNG.randn(4, 8).astype(np.float32))
    g = jnp.asarray(RNG.randn(4, 8).astype(np.float32))
    out = np.asarray(fused_swiglu(x, g))
    silu = np.asarray(x) / (1 + np.exp(-np.asarray(x)))
    np.testing.assert_allclose(out, silu * np.asarray(g), rtol=1e-4,
                               atol=1e-5)


def test_sparse_attention_matches_dense_mask():
    b, h, s, d = 1, 2, 8, 4
    q = RNG.randn(b, h, s, d).astype(np.float32)
    k = RNG.randn(b, h, s, d).astype(np.float32)
    v = RNG.randn(b, h, s, d).astype(np.float32)
    mask = np.tril(np.ones((s, s), np.float32))            # causal pattern
    full = np.broadcast_to(mask, (b * h, s, s)).reshape(b * h, s, s)
    sm = _coo(np.ascontiguousarray(full.reshape(b * h, s, s)))
    out = psp.nn.functional.attention(t(q), t(k), t(v), sm)
    lg = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
    lg = np.where(mask != 0, lg, -1e30)
    w = np.exp(lg - lg.max(-1, keepdims=True))
    w = w / w.sum(-1, keepdims=True)
    want = np.einsum("bhqk,bhkd->bhqd", w, v)
    np.testing.assert_allclose(np.asarray(out.numpy()), want,
                               rtol=1e-4, atol=1e-5)


def test_sparse_conv3d_and_subm():
    rng = np.random.RandomState(0)
    dense = np.zeros((1, 4, 4, 4, 2), np.float32)
    # a few active voxels
    for (d_, h_, w_) in [(0, 0, 0), (1, 2, 3), (3, 3, 1)]:
        dense[0, d_, h_, w_] = rng.randn(2)
    x = _coo(dense)
    conv = psp.nn.Conv3D(2, 3, kernel_size=3, padding=1)
    out = conv(x)
    assert out.shape == [1, 4, 4, 4, 3]
    # parity vs the dense conv on the same weights
    import jax
    import jax.numpy as jnp
    want = jax.lax.conv_general_dilated(
        jnp.asarray(dense), conv.weight._data, (1, 1, 1),
        [(1, 1), (1, 1), (1, 1)],
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
    np.testing.assert_allclose(np.asarray(out.to_dense().numpy()),
                               np.asarray(want), rtol=1e-4, atol=1e-5)

    sub = psp.nn.SubmConv3D(2, 3, kernel_size=3, padding=1)
    sout = sub(x)
    got = np.asarray(sout.to_dense().numpy())
    in_pat = np.abs(dense).sum(-1) != 0
    assert (np.abs(got).sum(-1) != 0).sum() <= in_pat.sum() * 1  # pattern kept
    assert np.all((np.abs(got).sum(-1) != 0) <= in_pat)
