"""Overlapped backward (ISSUE 5): tape grad-ready hooks, ready-bucket
async gradient exchange, fused donated optimizer step, persistent jit
cache, and the hapi trailing-partial-batch fix."""
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import nn
from paddle_tpu.autograd import tape
from paddle_tpu.distributed.comm import GradientBucketer


# ---------------------------------------------------------------------------
# tape grad-ready hooks
# ---------------------------------------------------------------------------


class TestGradReadyHooks:
    def _net(self):
        net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))
        wr = np.random.default_rng(0)
        for p in net.parameters():
            p.set_value(paddle.to_tensor(
                (wr.normal(size=p.shape) * 0.1).astype(np.float32)))
        return net

    def test_fires_once_per_leaf_in_finality_order(self):
        """Every trainable leaf fires exactly once per backward, and a
        leaf fires only when its grad is FINAL — the last layer's weight
        (whose consumers finish first in reverse traversal) fires before
        the first layer's."""
        net = self._net()
        fired = []
        cb = tape.register_grad_ready_callback(fired.append)
        try:
            x = paddle.to_tensor(np.ones((3, 4), np.float32))
            (net(x) ** 2).mean().backward()
        finally:
            tape.unregister_grad_ready_callback(cb)
        ids = [id(t) for t in fired]
        assert len(ids) == len(set(ids)), "a leaf fired twice"
        params = list(net.parameters())
        assert set(ids) == {id(p) for p in params}
        # grads were readable (final) inside the hook
        assert all(t.grad is not None for t in fired)
        w_first, w_last = params[0], params[2]
        assert ids.index(id(w_last)) < ids.index(id(w_first))

    def test_retain_graph_fires_per_backward(self):
        """retain_graph=True + a second backward: hooks fire once per
        leaf in EACH backward (the comm scheduler's stale-round discard
        keys on exactly this re-fire)."""
        net = self._net()
        fired = []
        cb = tape.register_grad_ready_callback(fired.append)
        try:
            x = paddle.to_tensor(np.ones((3, 4), np.float32))
            loss = (net(x) ** 2).mean()
            loss.backward(retain_graph=True)
            n1 = len(fired)
            loss.backward()
        finally:
            tape.unregister_grad_ready_callback(cb)
        nparams = len(list(net.parameters()))
        assert n1 == nparams
        assert len(fired) == 2 * nparams

    def test_double_backward_capture_does_not_fire(self):
        """paddle.grad (capture mode, accumulate=False) never owns .grad
        finality, so grad-ready must not fire there — only the final
        accumulate-mode backward over the second-order graph fires."""
        net = self._net()
        fired = []
        cb = tape.register_grad_ready_callback(fired.append)
        try:
            x = paddle.to_tensor(np.ones((3, 4), np.float32))
            loss = (net(x) ** 2).mean()
            (g,) = tape.grad(loss, [net[0].weight], create_graph=True)
            assert not fired, "capture-mode grad fired ready hooks"
            (g ** 2).sum().backward()
        finally:
            tape.unregister_grad_ready_callback(cb)
        assert fired, "double-backward's accumulate pass did not fire"
        assert all(t.grad is not None for t in fired)

    def test_unused_leaf_does_not_fire(self):
        """A parameter outside the backward graph must not fire (its
        bucket is the scheduler's at-barrier leftover path)."""
        used = paddle.create_parameter([4, 2])
        unused = paddle.create_parameter([4, 2])
        fired = []
        cb = tape.register_grad_ready_callback(fired.append)
        try:
            x = paddle.to_tensor(np.ones((3, 4), np.float32))
            paddle.matmul(x, used).sum().backward()
        finally:
            tape.unregister_grad_ready_callback(cb)
        assert id(unused) not in [id(t) for t in fired]
        assert id(used) in [id(t) for t in fired]


# ---------------------------------------------------------------------------
# single-tensor bucket fast path (satellite)
# ---------------------------------------------------------------------------


class TestSingleTensorBucket:
    def test_flatten_skips_assembly_with_identical_layout(self):
        """fuse 0 → every tensor its own bucket; the no-copy fast path
        must produce byte-identical flat vectors to the generic assembly
        loop (offset 0, no padding possible)."""
        params = [paddle.create_parameter([64, 32]),
                  paddle.create_parameter([32])]
        b = GradientBucketer(params, fuse_grad_size_in_MB=0)
        assert b.num_buckets == 2
        rng = np.random.default_rng(3)
        arrays = [rng.normal(size=(64, 32)).astype(np.float32),
                  rng.normal(size=(32,)).astype(np.float32)]
        for bi, bucket in enumerate(b._buckets):
            assert len(bucket.items) == 1
            fast = b._flatten(bucket, arrays)
            # generic path: force the assembly loop by temporarily
            # removing the single-item precondition
            (i, off, numel, shape) = bucket.items[0]
            ref = np.zeros(bucket.numel, bucket.dtype)
            ref[off:off + numel] = np.asarray(
                arrays[i], bucket.dtype).reshape(-1)
            np.testing.assert_array_equal(fast, ref)

    def test_fused_bucket_still_assembles(self):
        """A multi-tensor bucket keeps the generic layout (offsets in
        rank-deterministic parameter order)."""
        params = [paddle.create_parameter([8, 4]),
                  paddle.create_parameter([4])]
        b = GradientBucketer(params, fuse_grad_size_in_MB=32)
        assert b.num_buckets == 1
        rng = np.random.default_rng(4)
        arrays = [rng.normal(size=(8, 4)).astype(np.float32),
                  rng.normal(size=(4,)).astype(np.float32)]
        flat = b._flatten(b._buckets[0], arrays)
        np.testing.assert_array_equal(flat[:32], arrays[0].reshape(-1))
        np.testing.assert_array_equal(flat[32:36], arrays[1])


# ---------------------------------------------------------------------------
# dp-4 overlap parity (acceptance)
# ---------------------------------------------------------------------------


def _train_dp4(overlap, quant=None, fused_step=False, env=None, steps=3):
    """3-step dp-4 sim run through HybridParallelOptimizer; returns the
    per-rank parameter arrays."""
    saved = {}
    for k, v in (env or {}).items():
        saved[k] = os.environ.get(k)
        os.environ[k] = v

    def worker():
        r = dist.get_rank()
        model = nn.Sequential(nn.Linear(16, 32), nn.Tanh(),
                              nn.Linear(32, 4))
        wr = np.random.default_rng(0)
        for p in model.parameters():
            p.set_value(paddle.to_tensor(
                (wr.normal(size=p.shape) * 0.1).astype(np.float32)))
        strat = dist.fleet.DistributedStrategy()
        strat.hybrid_configs = {"dp_degree": 4}
        strat.comm_overlap = overlap
        strat.fuse_grad_size_in_MB = 0.0001     # several buckets in flight
        strat.comm_quantization = quant
        inner = paddle.optimizer.SGD(learning_rate=0.05,
                                     parameters=model.parameters())
        inner.fuse_step = fused_step
        opt = dist.fleet.HybridParallelOptimizer(inner, strategy=strat)
        loss_fn = nn.MSELoss()
        rngX = np.random.default_rng(7)
        X = rngX.normal(size=(4 * 8 * steps, 16)).astype(np.float32)
        Y = (X @ rngX.normal(size=(16, 4)).astype(np.float32)
             ).astype(np.float32)
        for s in range(steps):
            lo = (s * 4 + r) * 8
            loss = loss_fn(model(paddle.to_tensor(X[lo:lo + 8])),
                           paddle.to_tensor(Y[lo:lo + 8]))
            loss.backward()
            opt.step()
            opt.clear_grad()
        return [np.asarray(p.numpy()).copy() for p in model.parameters()]

    try:
        return dist.spawn(worker, nprocs=4).results
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


class TestOverlapParity:
    def test_dp4_bit_parity_on_off_and_env(self):
        """ISSUE 5 acceptance: after 3 dp-4 SGD steps the parameters are
        BIT-identical across (a) ready-bucket overlap, (b) strategy
        comm_overlap=False, and (c) PADDLE_COMM_OVERLAP=0 — the PR-1
        barrier path."""
        on = _train_dp4(True)
        off = _train_dp4(False)
        legacy = _train_dp4(True, env={"PADDLE_COMM_OVERLAP": "0"})
        for variant in (off, legacy):
            for rank_on, rank_v in zip(on, variant):
                for a, b in zip(rank_on, rank_v):
                    np.testing.assert_array_equal(a, b)
        # replicas agree with each other too
        for r in range(1, 4):
            for a, b in zip(on[0], on[r]):
                np.testing.assert_array_equal(a, b)

    def test_dp4_bit_parity_quantized(self):
        """Same exchange math (incl. int8 codec + error feedback) runs on
        the worker lanes — overlap on/off stays bit-identical."""
        on = _train_dp4(True, quant="int8")
        off = _train_dp4(False, quant="int8")
        for a, b in zip(on[0], off[0]):
            np.testing.assert_array_equal(a, b)

    def test_dp4_fused_step_bit_parity(self):
        """Fused donated SGD step under overlap == eager per-param loop,
        bit for bit (acceptance)."""
        eager = _train_dp4(True, fused_step=False)
        fused = _train_dp4(True, fused_step=True)
        for a, b in zip(eager[0], fused[0]):
            np.testing.assert_array_equal(a, b)

    def test_overlap_dispatches_in_backward(self):
        """The overlap run actually dispatches buckets DURING backward
        (telemetry `paddle_comm_overlap_buckets_total{where=in_backward}`
        grows)."""
        from paddle_tpu.distributed.comm.bucketer import _overlap_telemetry
        c = _overlap_telemetry()["buckets"]
        before = c.value(where="in_backward")
        _train_dp4(True)
        assert c.value(where="in_backward") > before


# ---------------------------------------------------------------------------
# fused step oracle (single process)
# ---------------------------------------------------------------------------


def _mk_params(shapes, seed=0):
    rng = np.random.default_rng(seed)
    params = []
    for s in shapes:
        p = paddle.create_parameter(list(s))
        p.set_value(paddle.to_tensor(
            rng.normal(size=s).astype(np.float32) * 0.1))
        params.append(p)
    return params


def _run_opt(opt_cls, fused, steps=3, seed=5, **kw):
    shapes = [(32, 16), (16,), (16, 8), (8,)] * 5      # 20 params >= min
    params = _mk_params(shapes)
    opt = opt_cls(learning_rate=0.05, parameters=params, **kw)
    opt.fuse_step = fused
    rng = np.random.default_rng(seed)
    grads = [[rng.normal(size=s).astype(np.float32) * 0.01 for s in shapes]
             for _ in range(steps)]
    for gs in grads:
        for p, g in zip(params, gs):
            p.grad = paddle.to_tensor(g)
        opt.step()
        opt.clear_grad()
    return [np.asarray(p.numpy()) for p in params]


class TestFusedStep:
    def test_sgd_bit_identical(self):
        """Plain-SGD fused step (two-phase delta/combine, no FMA across
        the final subtract) is bit-identical to the eager loop."""
        for a, b in zip(_run_opt(paddle.optimizer.SGD, False),
                        _run_opt(paddle.optimizer.SGD, True)):
            np.testing.assert_array_equal(a, b)

    def test_sgd_weight_decay_bit_identical(self):
        for a, b in zip(
                _run_opt(paddle.optimizer.SGD, False, weight_decay=0.01),
                _run_opt(paddle.optimizer.SGD, True, weight_decay=0.01)):
            np.testing.assert_array_equal(a, b)

    @pytest.mark.parametrize("opt_cls", ["Momentum", "Adam", "AdamW"])
    def test_slotted_optimizers_match_eager(self, opt_cls):
        """Slot-carrying optimizers run the generic one-call fused
        program — same math at f32 rounding level: the compiled program
        FMA-contracts the moment updates and evaluates bias-correction
        powers in f32 where the eager loop rounds per-op with f64
        python-float scalars, so updates agree to ~1e-6 absolute (params
        are O(0.1); near-zero elements make pure rtol meaningless)."""
        cls = getattr(paddle.optimizer, opt_cls)
        for a, b in zip(_run_opt(cls, False), _run_opt(cls, True)):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=5e-6)

    def test_fused_collapses_dispatches(self):
        """The telemetry counters show the O(params)->O(1) collapse: one
        eager dispatch per parameter per step vs O(1) fused calls."""
        from paddle_tpu.optimizer.fused import opt_telemetry
        c = opt_telemetry()["dispatches"]
        e0, f0 = c.value(mode="eager"), c.value(mode="fused")
        _run_opt(paddle.optimizer.SGD, False, steps=1)
        e1 = c.value(mode="eager")
        _run_opt(paddle.optimizer.SGD, True, steps=1)
        f1, e2 = c.value(mode="fused"), c.value(mode="eager")
        assert e1 - e0 == 20                    # one per param
        assert 0 < f1 - f0 <= 4                 # O(1) group calls
        assert e2 == e1                         # no eager leftovers
        assert (e1 - e0) / (f1 - f0) >= 10      # >= 10x collapse

    def test_l1_regularizer_falls_back_to_eager(self):
        """L1-regularized params are exotic: they must leave the fused
        path and still match the pure-eager result exactly."""
        from paddle_tpu.regularizer import L1Decay

        def run(fused):
            params = _mk_params([(8, 4)] * 20, seed=2)
            for p in params[:3]:
                p.regularizer = L1Decay(0.01)
            opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=params)
            opt.fuse_step = fused
            rng = np.random.default_rng(9)
            for p in params:
                p.grad = paddle.to_tensor(
                    rng.normal(size=(8, 4)).astype(np.float32) * 0.01)
            opt.step()
            return [np.asarray(p.numpy()) for p in params]

        for a, b in zip(run(False), run(True)):
            np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# overlap never deadlocks when a rank skips a step
# ---------------------------------------------------------------------------


class TestOverlapTimeout:
    def test_skipped_rank_times_out_not_deadlocks(self):
        """Rank 1 skips its backward+step; rank 0's in-flight bucket can
        never pair. The step boundary must surface a TimeoutError within
        the configured bound — not hang."""
        os.environ["PADDLE_COMM_OVERLAP_TIMEOUT_S"] = "3"
        try:
            def worker():
                r = dist.get_rank()
                model = nn.Linear(8, 4)
                model.weight.set_value(paddle.to_tensor(
                    np.ones((8, 4), np.float32) * 0.1))
                strat = dist.fleet.DistributedStrategy()
                strat.hybrid_configs = {"dp_degree": 2}
                strat.comm_overlap = True
                opt = dist.fleet.HybridParallelOptimizer(
                    paddle.optimizer.SGD(learning_rate=0.1,
                                         parameters=model.parameters()),
                    strategy=strat)
                if r == 1:
                    return "skipped"
                x = paddle.to_tensor(np.ones((2, 8), np.float32))
                model(x).sum().backward()
                opt.step()
                return "stepped"

            t0 = time.monotonic()
            # spawn wraps the rank's TimeoutError in its per-rank report
            with pytest.raises(RuntimeError, match="did not complete"):
                dist.spawn(worker, nprocs=2)
            assert time.monotonic() - t0 < 30
        finally:
            os.environ.pop("PADDLE_COMM_OVERLAP_TIMEOUT_S", None)


# ---------------------------------------------------------------------------
# persistent jit compilation cache (satellite)
# ---------------------------------------------------------------------------


class TestPersistentJitCache:
    def test_disk_hit_counted(self, tmp_path):
        import jax
        import jax.numpy as jnp

        from paddle_tpu.jit import api as jit_api

        cache_dir = str(tmp_path / "jitcache")
        prev = jit_api._PERSISTENT_CACHE[0]
        assert jit_api.enable_persistent_cache(cache_dir)
        try:
            c = jit_api._jit_metrics()["cache"]
            before = c.value(event="disk_hit")
            f = jax.jit(lambda x: x * 3 + 2)
            f(jnp.ones((4, 4))).block_until_ready()
            assert os.listdir(cache_dir), "no executables persisted"
            # drop the in-memory caches: the next call must restore the
            # compiled executable from disk, not recompile
            jax.clear_caches()
            f(jnp.ones((4, 4))).block_until_ready()
            assert c.value(event="disk_hit") > before
        finally:
            # restore the suite-wide cache (conftest enables one) rather
            # than leaving the plane disabled for every later test
            if isinstance(prev, str):
                jit_api._PERSISTENT_CACHE[0] = None
                jit_api.enable_persistent_cache(prev)
            else:
                jax.config.update("jax_compilation_cache_dir", None)
                jit_api._PERSISTENT_CACHE[0] = False

    def test_disabled_without_env(self, monkeypatch):
        from paddle_tpu.jit import api as jit_api
        monkeypatch.delenv("PADDLE_JIT_CACHE_DIR", raising=False)
        jit_api._PERSISTENT_CACHE[0] = None
        assert jit_api.enable_persistent_cache() is False
        jit_api._PERSISTENT_CACHE[0] = None


# ---------------------------------------------------------------------------
# hapi trailing-partial-batch recompile fix (satellite)
# ---------------------------------------------------------------------------


class _Toy(paddle.io.Dataset):
    def __init__(self, n=20):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        rng = np.random.default_rng(i)
        return (rng.normal(size=(8,)).astype(np.float32),
                rng.normal(size=(2,)).astype(np.float32))


class TestPartialBatchPad:
    def test_no_recompile_on_trailing_batch(self):
        """20 samples / batch 8 -> 8, 8, 4: the 4-row tail is padded to
        the compiled spec, so the jit cache records exactly ONE miss
        across three epochs (the old behavior traced a second program
        every epoch)."""
        from paddle_tpu.jit.api import _jit_metrics
        net = paddle.jit.to_static(nn.Sequential(
            nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2)))
        model = paddle.Model(net)
        model.prepare(
            optimizer=paddle.optimizer.SGD(
                learning_rate=0.01, parameters=net.parameters()),
            loss=nn.MSELoss())
        loader = paddle.io.DataLoader(_Toy(), batch_size=8, shuffle=False)
        c = _jit_metrics()["cache"]
        m0 = c.value(event="miss")
        model.fit(loader, epochs=3, verbose=0)
        assert c.value(event="miss") - m0 == 1

    def test_padded_gradients_match_unpadded(self):
        """Pad rows get a zero cotangent (outputs sliced before the
        loss), so the step on a padded tail equals the eager unpadded
        step."""
        def run(static):
            net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                                nn.Linear(16, 2))
            wr = np.random.default_rng(0)
            for p in net.parameters():
                p.set_value(paddle.to_tensor(
                    (wr.normal(size=p.shape) * 0.1).astype(np.float32)))
            if static:
                net = paddle.jit.to_static(net)
            model = paddle.Model(net)
            model.prepare(
                optimizer=paddle.optimizer.SGD(
                    learning_rate=0.05, parameters=net.parameters()),
                loss=nn.MSELoss())
            loader = paddle.io.DataLoader(_Toy(12), batch_size=8,
                                          shuffle=False)
            model.fit(loader, epochs=1, verbose=0)
            return [np.asarray(p.numpy()) for p in net.parameters()]

        for a, b in zip(run(False), run(True)):
            np.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-6)

    def test_batchnorm_disables_padding(self):
        """Batch-coupled normalization would see the pad rows in its
        statistics — the safety gate must keep such nets on the legacy
        per-shape trace."""
        net = paddle.jit.to_static(nn.Sequential(
            nn.Linear(8, 16), nn.BatchNorm1D(16), nn.Linear(16, 2)))
        model = paddle.Model(net)
        assert model._pad_partial_enabled() is False
