"""Unified runtime telemetry (ISSUE 2): metrics registry + span tracer
across train, comm, data, jit and serving paths — plus the profiler
satellite fixes (real chrome-trace timestamps, per-returning-step export,
live benchmark ips)."""
import json
import os
import re
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.profiler import (
    Profiler, ProfilerTarget, ProfilerState, make_scheduler,
    export_chrome_tracing, RecordEvent, benchmark, metrics, metrics_text,
    get_registry, get_tracer,
)
from paddle_tpu.profiler.telemetry import (
    MetricRegistry, SpanTracer, DEFAULT_LATENCY_BUCKETS,
)


# ---------------------------------------------------------------------------
# registry core
# ---------------------------------------------------------------------------

def test_counter_gauge_histogram_basics():
    r = MetricRegistry()
    c = r.counter("c_total", "help", labels=("kind",))
    c.inc(kind="a")
    c.inc(2, kind="a")
    c.inc(kind="b")
    assert c.value(kind="a") == 3
    assert c.value(kind="b") == 1

    g = r.gauge("g", "help")
    g.set(5)
    g.set_max(3)          # high-water: lower value must not win
    assert g.value() == 5
    g.set_max(9)
    assert g.value() == 9

    h = r.histogram("h_seconds", "help")
    for v in (0.001, 0.003, 0.02, 0.02, 4.0):
        h.observe(v)
    snap = r.collect()["h_seconds"]["series"][""]
    assert snap["count"] == 5
    assert abs(snap["sum"] - 4.044) < 1e-9
    assert snap["buckets"]["+Inf"] == 5
    # percentile estimate lands inside the right bucket
    assert 0.01 <= h.percentile(50) <= 0.025
    assert 2.5 <= h.percentile(99) <= 10.0


def test_registry_get_or_create_and_kind_mismatch():
    r = MetricRegistry()
    a = r.counter("x_total", "one")
    b = r.counter("x_total", "two")
    assert a is b
    with pytest.raises(TypeError):
        r.gauge("x_total")


def test_registry_reset_keeps_families():
    r = MetricRegistry()
    c = r.counter("c", labels=("k",))
    c.inc(k="x")
    h = r.histogram("h")
    h.observe(0.5)
    r.reset()
    snap = r.collect()
    assert snap["c"]["series"]["x"] == 0
    assert snap["h"]["series"][""]["count"] == 0


def test_histogram_concurrency_n_threads_one_histogram():
    """Satellite: N threads hammering one histogram — no lost updates."""
    r = MetricRegistry()
    h = r.histogram("conc_seconds", buckets=DEFAULT_LATENCY_BUCKETS)
    N, M = 8, 2000

    def work(i):
        for j in range(M):
            h.observe((j % 7) * 1e-3)

    threads = [threading.Thread(target=work, args=(i,)) for i in range(N)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = r.collect()["conc_seconds"]["series"][""]
    assert snap["count"] == N * M
    assert snap["buckets"]["+Inf"] == N * M
    # bucket counts are cumulative and monotone
    vals = [snap["buckets"][f"{b:g}"] for b in sorted(
        b for b in DEFAULT_LATENCY_BUCKETS)]
    assert vals == sorted(vals)


def test_prometheus_exposition_parses():
    r = MetricRegistry()
    r.counter("req_total", "requests", labels=("engine",)).inc(engine="static")
    r.gauge("depth", "queue depth").set(3)
    r.histogram("lat_seconds", "latency", labels=("engine",)).observe(
        0.02, engine="cont")
    text = r.to_text()
    line_re = re.compile(
        r'^(# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]*.*'
        r'|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [-+0-9.einfEINF]+)$')
    for line in text.splitlines():
        assert line_re.match(line), f"bad exposition line: {line!r}"
    assert '# TYPE req_total counter' in text
    assert 'req_total{engine="static"} 1' in text
    assert 'lat_seconds_bucket{engine="cont",le="+Inf"} 1' in text
    assert 'lat_seconds_count{engine="cont"} 1' in text


def test_jsonl_snapshot_export(tmp_path):
    r = MetricRegistry()
    r.counter("c_total").inc(7)
    path = str(tmp_path / "snap.jsonl")
    r.export_jsonl(path, extra={"run": "t"})
    r.export_jsonl(path)
    lines = open(path).read().splitlines()
    assert len(lines) == 2
    rec = json.loads(lines[0])
    assert rec["run"] == "t"
    assert rec["metrics"]["c_total"]["series"][""] == 7


# ---------------------------------------------------------------------------
# span tracer
# ---------------------------------------------------------------------------

def test_tracer_nesting_parent_linkage_and_real_ts():
    tr = SpanTracer()
    tr.enable()
    try:
        outer = tr.begin("outer")
        time.sleep(0.01)
        inner = tr.begin("inner")
        time.sleep(0.005)
        tr.end(inner)
        tr.end(outer)
    finally:
        tr.disable()
    spans = {s.name: s for s in tr.drain()}
    o, i = spans["outer"], spans["inner"]
    assert i.parent_id == o.span_id
    assert i.ts >= o.ts                     # inner begins after outer
    assert o.dur >= i.dur + 0.005           # outer covers inner
    assert o.tid == i.tid
    assert o.dur >= 0.015


def test_tracer_disabled_is_noop_and_threaded_tids():
    tr = SpanTracer()
    assert tr.begin("x") is None            # disabled: no-op
    tr.enable()
    barrier = threading.Barrier(3)   # all three alive at once, so thread
                                     # idents cannot be recycled

    def work(k):
        barrier.wait()
        sp = tr.begin(f"t{k}")
        tr.end(sp)
        barrier.wait()

    threads = [threading.Thread(target=work, args=(k,)) for k in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    tr.disable()
    spans = tr.drain()
    assert len(spans) == 3
    assert len({s.tid for s in spans}) == 3  # one tid per thread


# ---------------------------------------------------------------------------
# make_scheduler edges (satellite)
# ---------------------------------------------------------------------------

def test_make_scheduler_skip_first_only_delays_cycle():
    sch = make_scheduler(closed=0, ready=0, record=2, skip_first=3)
    assert [sch(i) for i in range(3)] == [ProfilerState.CLOSED] * 3
    assert sch(3) == ProfilerState.RECORD
    assert sch(4) == ProfilerState.RECORD_AND_RETURN
    assert sch(5) == ProfilerState.RECORD   # repeat=0: cycles forever
    assert sch(6) == ProfilerState.RECORD_AND_RETURN


def test_make_scheduler_record_one_returns_every_cycle():
    sch = make_scheduler(closed=1, ready=0, record=1)
    states = [sch(i) for i in range(6)]
    assert states == [ProfilerState.CLOSED, ProfilerState.RECORD_AND_RETURN] * 3


def test_make_scheduler_repeat_exhausts_to_closed():
    sch = make_scheduler(closed=0, ready=1, record=1, repeat=2)
    assert [sch(i) for i in range(6)] == [
        ProfilerState.READY, ProfilerState.RECORD_AND_RETURN,
        ProfilerState.READY, ProfilerState.RECORD_AND_RETURN,
        ProfilerState.CLOSED, ProfilerState.CLOSED]


# ---------------------------------------------------------------------------
# profiler satellites: real trace ts, per-returning-step export, live ips
# ---------------------------------------------------------------------------

def test_chrome_trace_real_timestamps_and_nesting(tmp_path):
    traces = str(tmp_path / "tr")
    with Profiler(targets=[ProfilerTarget.CPU],
                  on_trace_ready=export_chrome_tracing(traces)) as p:
        x = paddle.randn([16, 16])
        with RecordEvent("outer_region"):
            y = x @ x
            time.sleep(0.01)
            _ = y.sum()
        p.step()
    path = os.path.join(traces, os.listdir(traces)[0])
    data = json.load(open(path))
    events = data["traceEvents"]
    assert events
    by_name = {}
    for e in events:
        by_name.setdefault(e["name"], []).append(e)
    outer = by_name["outer_region"][0]
    # REAL timestamps: not the old fabricated cumulative layout where
    # event k started exactly at sum(dur[:k])
    assert not all(e["args"].get("synthetic_ts") for e in events)
    assert outer["dur"] >= 10_000            # µs; covers the sleep
    ops = [e for n, es in by_name.items() if n != "outer_region" for e in es]
    assert ops
    for e in ops:
        # ops ran INSIDE the region: real begin/end nest inside it
        assert e["ts"] >= outer["ts"] - 1
        assert e["ts"] + e["dur"] <= outer["ts"] + outer["dur"] + 1
        assert e["args"].get("parent_id") == outer["args"]["span_id"]
    assert "tid" in outer


def test_export_fires_on_every_returning_step():
    """Satellite: a scheduler yielding RECORD_AND_RETURN on consecutive
    steps must export once per returning step, not once per state change."""
    calls = []
    with Profiler(targets=[ProfilerTarget.CPU],
                  scheduler=lambda step: ProfilerState.RECORD_AND_RETURN,
                  on_trace_ready=lambda prof: calls.append(prof._step)) as p:
        x = paddle.randn([4])
        for _ in range(3):
            _ = x + 1
            p.step()
    # 3 returning in-loop steps + the final stop() flush
    assert len(calls) >= 3
    assert calls[:3] == [1, 2, 3]


def test_benchmark_ips_is_live_while_running():
    b = benchmark()
    b.begin()
    b.step(num_samples=100)
    first = b.ips()
    time.sleep(0.05)
    second = b.ips()                 # still running: elapsed keeps growing
    assert second < first
    b.end()
    final = b.ips()
    time.sleep(0.02)
    assert b.ips() == final          # stopped: latched


# ---------------------------------------------------------------------------
# instrumented layers
# ---------------------------------------------------------------------------

def _series_populated(snap, name):
    fam = snap.get(name)
    if not fam:
        return False
    return any((s if isinstance(s, (int, float)) else s.get("count", 0)) > 0
               for s in fam["series"].values())


def test_tape_op_telemetry_counts_ops():
    from paddle_tpu.profiler.telemetry import op_telemetry
    reg = get_registry()
    with op_telemetry():
        x = paddle.to_tensor(np.ones((4, 4), np.float32))
        for _ in range(3):
            _ = x @ x
    c = reg.counter("paddle_op_dispatch_total", labels=("op",))
    assert c.value(op="matmul") >= 3
    before = c.value(op="matmul")
    _ = x @ x                        # telemetry off: no counting
    assert c.value(op="matmul") == before


def test_jit_cache_and_compile_metrics():
    reg = get_registry()
    cache = reg.counter("paddle_jit_cache_total", labels=("event",))
    h = reg.histogram("paddle_jit_compile_seconds")
    miss0 = cache.value(event="miss")
    hit0 = cache.value(event="hit")
    n0 = reg.collect()["paddle_jit_compile_seconds"]["series"].get(
        "", {"count": 0})["count"]

    @paddle.jit.to_static
    def f(a):
        return a * 3 + 1

    t = paddle.to_tensor(np.ones((8,), np.float32))
    f(t)
    f(t)
    f(paddle.to_tensor(np.ones((4,), np.float32)))   # new spec: miss
    assert cache.value(event="miss") == miss0 + 2
    assert cache.value(event="hit") == hit0 + 1
    snap = reg.collect()["paddle_jit_compile_seconds"]["series"][""]
    assert snap["count"] == n0 + 2
    assert snap["sum"] > 0


def test_comm_stats_bridge_into_registry():
    from paddle_tpu.distributed.comm import get_comm_stats
    reg = get_registry()
    calls = reg.counter("paddle_comm_collectives_total", labels=("kind",))
    wire = reg.counter("paddle_comm_wire_bytes_total", labels=("kind",))
    c0 = calls.value(kind="bridge_test")
    get_comm_stats().record("bridge_test", 4000, 1000, max_error=0.25)
    assert calls.value(kind="bridge_test") == c0 + 1
    assert wire.value(kind="bridge_test") >= 1000
    assert reg.gauge("paddle_comm_quant_max_error").value() >= 0.25


def test_dataloader_batch_wait_and_queue_metrics():
    from paddle_tpu.io import DataLoader, TensorDataset
    reg = get_registry()
    n0 = reg.collect().get("paddle_dataloader_batches_total",
                           {"series": {"": 0}})["series"].get("", 0)
    X = paddle.to_tensor(np.random.randn(24, 4).astype(np.float32))
    Y = paddle.to_tensor(np.arange(24).reshape(24, 1))
    loader = DataLoader(TensorDataset([X, Y]), batch_size=6)
    seen = sum(1 for _ in loader)
    assert seen == 4
    snap = metrics()
    assert snap["paddle_dataloader_batches_total"]["series"][""] == n0 + 4
    assert snap["paddle_dataloader_batch_wait_seconds"]["series"][""][
        "count"] >= 4
    assert "paddle_dataloader_queue_depth" in snap


# ---------------------------------------------------------------------------
# end-to-end: six layers in one snapshot (acceptance)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_llama():
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    paddle.seed(0)
    return LlamaForCausalLM(llama_tiny(num_hidden_layers=2))


def test_serving_latency_histograms_from_continuous_engine(tiny_llama):
    """Satellite: a ContinuousServingEngine run populates queue-wait,
    TTFT, decode-step and per-token histograms + slot/page gauges."""
    from paddle_tpu.inference import ContinuousServingEngine
    reg = get_registry()
    # a merely-STARTED engine elsewhere in the suite creates the family
    # with no series yet — tolerate family-present-series-absent too
    before = reg.collect().get("paddle_serving_decode_step_seconds")
    n0 = (before["series"].get("", {}).get("count", 0) if before else 0)
    eng = ContinuousServingEngine(tiny_llama, max_batch_size=2, max_len=64)
    with eng:
        out = eng.generate(np.arange(5)[None], max_new_tokens=4, timeout=300)
    assert out.shape[1] == 9
    snap = metrics()
    ttft = snap["paddle_serving_ttft_seconds"]["series"]["continuous"]
    assert ttft["count"] >= 1
    assert ttft["sum"] > 0
    qw = snap["paddle_serving_queue_wait_seconds"]["series"]["continuous"]
    assert qw["count"] >= 1
    dec = snap["paddle_serving_decode_step_seconds"]["series"][""]
    assert dec["count"] >= n0 + 3            # ≥3 decode steps for 4 tokens
    tok = snap["paddle_serving_token_latency_seconds"]["series"][""]
    assert tok["count"] >= 3
    assert snap["paddle_serving_tokens_generated_total"]["series"][
        "continuous"] >= 4
    assert "paddle_serving_active_slots" in snap
    assert "paddle_serving_free_pages" in snap
    # all slots freed at the end
    assert snap["paddle_serving_free_slots"]["series"][""] >= 1


def test_telemetry_callback_end_to_end_fit(tiny_llama):
    """Satellite: TelemetryCallback in a tiny fit loop records step time,
    throughput, MFU and enables per-op telemetry for the duration."""
    from paddle_tpu.hapi import Model
    from paddle_tpu.callbacks import TelemetryCallback
    from paddle_tpu.io import TensorDataset
    import paddle_tpu.nn as nn

    paddle.seed(0)
    net = nn.Linear(6, 3)
    m = Model(net)
    m.prepare(optimizer=paddle.optimizer.SGD(learning_rate=0.1,
                                             parameters=net.parameters()),
              loss=nn.CrossEntropyLoss())
    X = paddle.to_tensor(np.random.randn(16, 6).astype(np.float32))
    Y = paddle.to_tensor(np.random.randint(0, 3, (16, 1)))
    reg = get_registry()
    steps0 = reg.counter("paddle_train_steps_total").value()
    cb = TelemetryCallback(samples_per_batch=4, tokens_per_batch=24,
                           step_flops=1e6)
    m.fit(TensorDataset([X, Y]), batch_size=4, epochs=1, callbacks=[cb],
          verbose=0)
    snap = metrics()
    assert reg.counter("paddle_train_steps_total").value() == steps0 + 4
    st = snap["paddle_train_step_seconds"]["series"][""]
    assert st["count"] >= 4 and st["sum"] > 0
    assert snap["paddle_train_samples_per_sec"]["series"][""] > 0
    assert snap["paddle_train_tokens_per_sec"]["series"][""] > 0
    assert snap["paddle_train_mfu_ratio"]["series"][""] > 0
    # op telemetry was live during fit (tape layer populated)
    assert _series_populated(snap, "paddle_op_dispatch_total")
    # ...and switched off again after on_train_end
    from paddle_tpu.autograd import tape
    from paddle_tpu.profiler.telemetry import _observe_op
    assert _observe_op not in tape._op_observers


def test_metrics_facade_covers_all_six_layers(tiny_llama):
    """Acceptance: after a simulated train step + a continuous-engine
    generate, ``paddle.profiler.metrics()`` carries populated series from
    tape, jit, comm, io, serving and the train callback — and
    ``metrics_text()`` parses as Prometheus exposition."""
    # self-sufficient when run alone: top up any layer the earlier tests
    # in this file would normally have populated
    snap = metrics()
    if not _series_populated(snap, "paddle_op_dispatch_total"):
        from paddle_tpu.profiler.telemetry import op_telemetry
        with op_telemetry():
            x = paddle.to_tensor(np.ones((2, 2), np.float32))
            _ = x + x
    if not _series_populated(snap, "paddle_jit_cache_total"):
        f = paddle.jit.to_static(lambda a: a * 2)
        f(paddle.to_tensor(np.ones((2,), np.float32)))
    if not _series_populated(snap, "paddle_comm_collectives_total"):
        from paddle_tpu.distributed.comm import get_comm_stats
        get_comm_stats().record("facade", 8, 8)
    if not _series_populated(snap, "paddle_dataloader_batches_total"):
        from paddle_tpu.io import DataLoader, TensorDataset
        X = paddle.to_tensor(np.ones((4, 2), np.float32))
        for _ in DataLoader(TensorDataset([X]), batch_size=2):
            pass
    if not _series_populated(snap, "paddle_serving_ttft_seconds"):
        from paddle_tpu.inference import ContinuousServingEngine
        eng = ContinuousServingEngine(tiny_llama, max_batch_size=1,
                                      max_len=32)
        with eng:
            eng.generate(np.arange(3)[None], max_new_tokens=2, timeout=300)
    if not _series_populated(snap, "paddle_train_step_seconds"):
        from paddle_tpu.callbacks import TelemetryCallback
        cb = TelemetryCallback(track_memory=False)
        cb.on_train_begin({})
        cb.on_train_batch_begin(0, {})
        cb.on_train_batch_end(0, {})
        cb.on_train_end({})
    snap = metrics()
    for name in ("paddle_op_dispatch_total",         # autograd tape
                 "paddle_jit_cache_total",           # jit/to_static
                 "paddle_comm_collectives_total",    # distributed.comm
                 "paddle_dataloader_batches_total",  # io.DataLoader
                 "paddle_serving_ttft_seconds",      # serving engines
                 "paddle_train_step_seconds"):       # TelemetryCallback
        assert _series_populated(snap, name), f"layer not populated: {name}"
    text = metrics_text()
    line_re = re.compile(
        r'^(# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]*.*'
        r'|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [-+0-9.einfEINF]+)$')
    for line in text.splitlines():
        assert line_re.match(line), f"bad exposition line: {line!r}"
