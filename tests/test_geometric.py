"""paddle.geometric segment + message-passing ops vs numpy oracles
(reference: test/legacy_test/test_segment_ops.py, test_graph_send_recv)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import geometric as G

RNG = np.random.RandomState(0)


def t(x):
    return paddle.to_tensor(np.asarray(x))


def test_segment_ops():
    data = RNG.randn(6, 3).astype(np.float32)
    ids = np.array([0, 0, 1, 1, 1, 3], np.int64)   # segment 2 empty
    got = np.asarray(G.segment_sum(t(data), t(ids)).numpy())
    want = np.zeros((4, 3), np.float32)
    for i, s in enumerate(ids):
        want[s] += data[i]
    np.testing.assert_allclose(got, want, rtol=1e-6)

    gm = np.asarray(G.segment_mean(t(data), t(ids)).numpy())
    np.testing.assert_allclose(gm[0], data[:2].mean(0), rtol=1e-6)
    np.testing.assert_allclose(gm[2], 0.0)          # empty -> 0

    gx = np.asarray(G.segment_max(t(data), t(ids)).numpy())
    np.testing.assert_allclose(gx[1], data[2:5].max(0), rtol=1e-6)
    np.testing.assert_allclose(gx[2], 0.0)
    gn = np.asarray(G.segment_min(t(data), t(ids)).numpy())
    np.testing.assert_allclose(gn[1], data[2:5].min(0), rtol=1e-6)


def test_send_u_recv_and_grad():
    x = RNG.randn(4, 2).astype(np.float32)
    src = np.array([0, 1, 2, 3, 1], np.int64)
    dst = np.array([1, 2, 1, 0, 0], np.int64)
    out = np.asarray(G.send_u_recv(t(x), t(src), t(dst),
                                   reduce_op="sum").numpy())
    # reference semantics: output has x.shape[0] rows — node 3 has no
    # incoming edge and keeps a zero row
    want = np.zeros((4, 2), np.float32)
    for s, d in zip(src, dst):
        want[d] += x[s]
    np.testing.assert_allclose(out, want, rtol=1e-6)

    xt = t(x)
    xt.stop_gradient = False
    G.send_u_recv(xt, t(src), t(dst), reduce_op="sum",
                  out_size=4).sum().backward()
    g = np.asarray(xt.grad.numpy())
    # node 1 feeds two edges -> grad 2, others 1
    np.testing.assert_allclose(g[:, 0], [1, 2, 1, 1], rtol=1e-6)


def test_send_ue_recv_and_uv():
    x = RNG.randn(3, 2).astype(np.float32)
    e = RNG.randn(4, 2).astype(np.float32)
    src = np.array([0, 1, 2, 0], np.int64)
    dst = np.array([1, 0, 0, 2], np.int64)
    out = np.asarray(G.send_ue_recv(t(x), t(e), t(src), t(dst),
                                    message_op="mul",
                                    reduce_op="max").numpy())
    msgs = x[src] * e
    want = np.full((3, 2), -np.inf, np.float32)   # 3 nodes here
    for i, d in enumerate(dst):
        want[d] = np.maximum(want[d], msgs[i])
    want[np.isinf(want)] = 0.0
    np.testing.assert_allclose(out, want, rtol=1e-5)

    uv = np.asarray(G.send_uv(t(x), t(x), t(src), t(dst),
                              message_op="sub").numpy())
    np.testing.assert_allclose(uv, x[src] - x[dst], rtol=1e-6)


def test_jit_with_out_size():
    x = RNG.randn(5, 2).astype(np.float32)
    ids = np.array([0, 1, 1, 2, 2], np.int64)

    def fn(a):
        # num_segments passed explicitly: traceable, no graph break
        return G.segment_sum(a, t(ids), num_segments=3)

    static = paddle.jit.to_static(fn)
    got = np.asarray(static(t(x)).numpy())
    np.testing.assert_allclose(got, np.asarray(fn(t(x)).numpy()),
                               rtol=1e-6)
