"""Tests for the first-compile guard (utils.guarded_compile) — the
round-2 post-mortem hardening: a deliberately-hung canary compile must
be killed by the timeout and latched as quarantined (VERDICT.md round-2
"Next round" item 1)."""
import os
import time

import pytest

from paddle_tpu.utils import guarded_compile as gc


@pytest.fixture
def proof_dir(tmp_path, monkeypatch):
    d = str(tmp_path / "proofs")
    monkeypatch.setenv("PADDLE_TPU_KERNEL_PROOF_DIR", d)
    return d


def test_prove_ok_latches(proof_dir):
    assert gc.status("k1") == "unknown"
    assert gc.prove("k1", timeout=30, src="print('PROOF_OK')") is True
    assert gc.status("k1") == "ok"
    # idempotent: latched result returned without re-running
    assert gc.prove("k1", timeout=30, src="raise SystemExit(1)") is True


def test_prove_timeout_quarantines(proof_dir):
    t0 = time.perf_counter()
    ok = gc.prove("hang", timeout=3,
                  src="import time; time.sleep(600); print('PROOF_OK')")
    dt = time.perf_counter() - t0
    assert ok is False
    assert dt < 60          # the hang was killed, not waited out
    assert gc.status("hang") == "bad"
    # a latched-bad kernel is NEVER implicitly retried
    t1 = time.perf_counter()
    assert gc.prove("hang", timeout=3, src="print('PROOF_OK')") is False
    assert time.perf_counter() - t1 < 1.0
    # explicit clear() un-quarantines
    gc.clear("hang")
    assert gc.status("hang") == "unknown"
    assert gc.prove("hang", timeout=30, src="print('PROOF_OK')") is True


def test_prove_skip_latches_nothing(proof_dir):
    # a canary that can't answer (e.g. wrong backend) must not poison
    # the latch — transient conditions are not evidence about the kernel
    ok = gc.prove("skippy", timeout=30,
                  src="print('PROOF_SKIP: no tpu'); raise SystemExit(3)")
    assert ok is False
    assert gc.status("skippy") == "unknown"
    assert gc.prove("skippy", timeout=30, src="print('PROOF_OK')") is True


def test_real_canary_skips_on_cpu_host(proof_dir):
    # the shipped canaries refuse to latch anything on a non-TPU backend
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    src = ("import jax; jax.config.update('jax_platforms', 'cpu')\n"
           + gc.CANARIES["quant_matmul"])
    assert gc.prove("quant_matmul", timeout=120, src=src, env=env) is False
    assert gc.status("quant_matmul") == "unknown"


def test_prove_failure_quarantines(proof_dir):
    assert gc.prove("boom", timeout=30,
                    src="raise RuntimeError('no')") is False
    assert gc.status("boom") == "bad"
    # failure note is recorded in the marker for diagnosis
    with open(os.path.join(proof_dir, "boom.bad")) as f:
        assert "RuntimeError" in f.read()


def test_kernel_allowed_modes(proof_dir, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_KERNEL_GUARD", "strict")
    with pytest.warns(RuntimeWarning, match="not been proven"):
        assert gc.kernel_allowed("fa") is False
    monkeypatch.setenv("PADDLE_TPU_KERNEL_GUARD", "trust")
    assert gc.kernel_allowed("fa") is True
    monkeypatch.setenv("PADDLE_TPU_KERNEL_GUARD", "off")
    assert gc.kernel_allowed("fa") is True
    # proven-ok passes in strict; latched-bad blocks even in trust
    gc.prove("fa", timeout=30, src="print('PROOF_OK')")
    monkeypatch.setenv("PADDLE_TPU_KERNEL_GUARD", "strict")
    assert gc.kernel_allowed("fa") is True
    gc.clear("fa")
    gc.prove("fa", timeout=30, src="raise SystemExit(1)")
    monkeypatch.setenv("PADDLE_TPU_KERNEL_GUARD", "trust")
    with pytest.warns(RuntimeWarning, match="quarantined"):
        assert gc.kernel_allowed("fa") is False


def test_flash_attention_gate_respects_guard(proof_dir, monkeypatch):
    """The flash entry point consults the guard only on real TPU and
    falls back to the XLA reference when unproven (gate logic tested by
    monkeypatching the backend probe; no Mosaic compile happens)."""
    import importlib
    import jax
    fa = importlib.import_module("paddle_tpu.ops.pallas.flash_attention")
    monkeypatch.setenv("PADDLE_TPU_KERNEL_GUARD", "strict")
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    with pytest.warns(RuntimeWarning, match="not been proven"):
        assert fa._mosaic_allowed() is False
    gc.prove("flash_attention", timeout=30, src="print('PROOF_OK')")
    assert fa._mosaic_allowed() is True
    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
    gc.clear("flash_attention")
    assert fa._mosaic_allowed() is True   # guard only engages on TPU


def test_canaries_registered():
    # every guarded call site has a canary; bench needs resolve
    for k in ("flash_attention", "paged_attention", "quant_matmul",
              "ring_attention"):
        assert k in gc.CANARIES
        assert "PROOF_OK" in gc.CANARIES[k]
    for mode in ("resnet", "llama", "llama_decode", "data"):
        for k in gc.bench_kernels(mode):
            assert gc._canary_src(k, missing_ok=True) is not None, k


def test_cli(proof_dir, capsys):
    assert gc.main(["prove", "nosuch"]) == 2           # unknown kernel id
    assert gc.main(["status", "flash_attention"]) == 0
    out = capsys.readouterr().out
    assert "flash_attention unknown" in out
    assert gc.main(["clear", "flash_attention"]) == 0
