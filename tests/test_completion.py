"""Auto-parallel completion inspection (VERDICT.md round-3 missing item
6; reference: ``auto_parallel/static/completion.py`` dist-attr
propagation + the ``test/auto_parallel/`` structural assertions).

GSPMD does the propagation; the Completer makes it INSPECTABLE: resolved
input/output specs and per-framework-op intermediate shardings captured
through the tape dispatch hook."""
import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.auto_parallel import (Completer, ProcessMesh,
                                                  Shard, shard_tensor)


def _mesh2d():
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    return Mesh(devs, ("dp", "mp"))


def test_matmul_propagation_specs():
    mesh = _mesh2d()
    x = jax.device_put(jnp.ones((8, 16)), NamedSharding(mesh, P("dp", None)))
    w = jax.device_put(jnp.ones((16, 32)), NamedSharding(mesh, P(None, "mp")))

    def f(xt, wt):
        return (xt @ wt).tanh()

    report = Completer(mesh).complete(f, x, w)
    assert report.input_spec(0) == ("dp", None)
    assert report.input_spec(1) == (None, "mp")
    # GSPMD completes the output to split over BOTH axes
    assert report.output_spec(0) == ("dp", "mp")
    # intermediates captured per framework op, with propagated placements
    ops = dict(report.op_specs())
    assert any(l.startswith("matmul") for l in ops), ops
    assert any(l.startswith("tanh") for l in ops), ops
    mm = [s for l, s in report.op_specs(r"^matmul")][0]
    assert mm == ("dp", "mp"), mm
    assert report.histogram()          # non-empty census


def test_completion_through_layers_and_dist_tensors():
    """The user-facing chain: shard_tensor placements + a real nn model
    — the Completer reports what every Linear's output resolved to."""
    mesh = _mesh2d()
    pmesh = ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["dp", "mp"])
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 16))
    x = shard_tensor(np.ones((8, 16), np.float32), pmesh,
                     [Shard(0), paddle.distributed.auto_parallel.Replicate()])

    report = Completer(mesh).complete(lambda t: net(t), x)
    assert report.input_spec(0)[0] == "dp"
    linears = report.op_specs(r"^linear")
    assert len(linears) == 2
    for label, spec in linears:
        assert spec[0] == "dp", (label, spec)   # batch stays dp-split
    assert report.output_spec(0)[0] == "dp"


def test_replicated_fallback_is_visible():
    """A reduction to scalar cannot stay sharded — the report shows the
    fallback instead of hiding it (the 'no silent replication' check the
    reference suites do on dist_attrs)."""
    mesh = _mesh2d()
    x = jax.device_put(jnp.ones((8, 16)), NamedSharding(mesh, P("dp", "mp")))
    report = Completer(mesh).complete(lambda t: t.sum(), x)
    assert report.output_spec(0) == ()
    ops = report.op_specs(r"^sum")
    assert ops and ops[0][1] in ((), None, "()"), ops
