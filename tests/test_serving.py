"""Batched serving engine (reference: Paddle Inference request batching
around the fused decode tier; VERDICT round-1 L11 'no serving tier')."""
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import ServingEngine
from paddle_tpu.models import LlamaForCausalLM, llama_tiny


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    return LlamaForCausalLM(llama_tiny(num_hidden_layers=2))


def test_concurrent_requests_batched_and_correct(model):
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, 128, (1, 6)).astype(np.int64) for _ in range(4)]
    # sequential oracle
    oracle = [np.asarray(model.generate(paddle.to_tensor(p),
                                        max_new_tokens=5)._data)
              for p in prompts]

    eng = ServingEngine(model, max_batch_size=4, batch_window_s=0.25)
    with eng:
        results = [None] * 4

        def call(i):
            results[i] = np.asarray(
                eng.generate(prompts[i], max_new_tokens=5, timeout=300)
                .numpy())

        threads = [threading.Thread(target=call, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    for got, want in zip(results, oracle):
        np.testing.assert_array_equal(got, want)
    # the window collected them into fewer model calls than requests
    assert eng.batches_run < 4, eng.batches_run


def test_incompatible_lengths_get_separate_batches(model):
    rng = np.random.RandomState(1)
    a = rng.randint(0, 128, (1, 4)).astype(np.int64)
    b = rng.randint(0, 128, (1, 9)).astype(np.int64)
    eng = ServingEngine(model, max_batch_size=4, batch_window_s=0.05)
    with eng:
        out = [None, None]

        def call(i, p):
            out[i] = eng.generate(p, max_new_tokens=3, timeout=300)

        ts = [threading.Thread(target=call, args=(0, a)),
              threading.Thread(target=call, args=(1, b))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    assert out[0].shape[1] == 4 + 3 and out[1].shape[1] == 9 + 3
    assert eng.batches_run == 2


def test_error_fans_out_and_engine_survives(model):
    eng = ServingEngine(model, max_batch_size=2, batch_window_s=0.01)
    with eng:
        bad = np.zeros((1, 0), np.int64)      # empty prompt -> error
        with pytest.raises(Exception):
            eng.generate(bad, max_new_tokens=2, timeout=300)
        ok = eng.generate(np.ones((1, 4), np.int64), max_new_tokens=2,
                          timeout=300)
        assert ok.shape[1] == 6


def test_requires_start(model):
    eng = ServingEngine(model)
    with pytest.raises(RuntimeError, match="start"):
        eng.generate(np.ones((1, 4), np.int64))


def test_stop_start_cycle_and_stranded_requests(model):
    eng = ServingEngine(model, max_batch_size=2, batch_window_s=0.01)
    eng.start()
    eng.stop()
    eng.stop()                      # double stop must be harmless
    eng.start()                     # restart: stale stop tokens drained
    out = eng.generate(np.ones((1, 4), np.int64), max_new_tokens=2,
                       timeout=300)
    assert out.shape[1] == 6
    eng.stop()
    with pytest.raises(RuntimeError, match="not started"):
        eng.generate(np.ones((1, 4), np.int64))
