"""Higher-order autograd: paddle.grad(create_graph=True) (VERDICT.md round-1
item 5; reference: the eager double-grad generated nodes —
``paddle/fluid/eager/api/generated`` higher-order paths — exercised upstream
by test_imperative_double_grad.py / gradient-penalty GAN recipes)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def _leaf(val):
    t = paddle.to_tensor(np.asarray(val, np.float32))
    t.stop_gradient = False
    return t


def test_double_grad_polynomial():
    # y = x^3: dy/dx = 3x^2, d2y/dx2 = 6x
    x = _leaf([1.0, 2.0, -3.0])
    y = (x * x * x).sum()
    (g1,) = paddle.grad(y, x, create_graph=True)
    np.testing.assert_allclose(g1.numpy(), 3 * np.array([1, 4, 9.0]),
                               rtol=1e-5)
    (g2,) = paddle.grad(g1.sum(), x)
    np.testing.assert_allclose(g2.numpy(), 6 * np.array([1, 2, -3.0]),
                               rtol=1e-5)


def test_triple_grad():
    # y = x^4: y''' = 24x
    x = _leaf([0.5, -1.5])
    y = (x ** paddle.to_tensor(4.0)).sum()
    (g1,) = paddle.grad(y, x, create_graph=True)
    (g2,) = paddle.grad(g1.sum(), x, create_graph=True)
    (g3,) = paddle.grad(g2.sum(), x)
    np.testing.assert_allclose(g3.numpy(), 24 * np.array([0.5, -1.5]),
                               rtol=1e-4)


def test_double_grad_backward_into_weights():
    """Gradient penalty: d/dW of ||dD/dx||^2 must be nonzero — the grads
    returned by create_graph=True connect to every requires-grad leaf the
    subgraph touches, not just `inputs` (the WGAN-GP contract)."""
    paddle.seed(3)
    lin = paddle.nn.Linear(4, 1)
    x = _leaf(np.random.RandomState(0).randn(5, 4))
    out = paddle.tanh(lin(x)).sum()
    (gx,) = paddle.grad(out, x, create_graph=True)
    penalty = (gx * gx).sum()
    penalty.backward()
    w_grad = lin.weight.grad
    assert w_grad is not None
    assert float(np.abs(w_grad.numpy()).sum()) > 1e-6
    # numeric check: perturb one weight entry, redo the penalty
    eps = 1e-3
    i, j = 1, 0
    base = float(penalty.numpy())

    def penalty_at(delta):
        lin.weight._data = lin.weight._data.at[i, j].add(delta)
        x2 = _leaf(np.random.RandomState(0).randn(5, 4))
        o = paddle.tanh(lin(x2)).sum()
        (g,) = paddle.grad(o, x2, create_graph=True)
        p = float(((g * g).sum()).numpy())
        lin.weight._data = lin.weight._data.at[i, j].add(-delta)
        return p

    num = (penalty_at(eps) - penalty_at(-eps)) / (2 * eps)
    np.testing.assert_allclose(float(w_grad.numpy()[i, j]), num,
                               rtol=5e-2, atol=1e-4)
    assert abs(base - float(penalty.numpy())) < 1e-8


def test_double_grad_matmul_chain():
    # z = (x @ w).square().sum(); d2z/dx2 = 2 w w^T (per row)
    rng = np.random.RandomState(1)
    x = _leaf(rng.randn(3, 4))
    w = paddle.to_tensor(rng.randn(4, 2).astype(np.float32))
    z = paddle.square(paddle.matmul(x, w)).sum()
    (g1,) = paddle.grad(z, x, create_graph=True)
    (g2,) = paddle.grad(g1.sum(), x)
    want = np.broadcast_to(2 * (w.numpy() @ w.numpy().T).sum(1), (3, 4))
    np.testing.assert_allclose(g2.numpy(), want, rtol=1e-4, atol=1e-5)


def test_grad_outputs_seed_differentiable():
    x = _leaf([2.0])
    s = _leaf([3.0])
    y = x * x
    (g,) = paddle.grad(y, x, grad_outputs=[s], create_graph=True)  # g = 2xs
    np.testing.assert_allclose(g.numpy(), [12.0])
    (gs,) = paddle.grad(g, s)    # dg/ds = 2x
    np.testing.assert_allclose(gs.numpy(), [4.0])


def test_allow_unused_contract():
    x = _leaf([1.0])
    z = _leaf([1.0])
    y = x * 2.0
    with pytest.raises(ValueError):
        paddle.grad(y, [x, z], create_graph=True)
    gx, gz = paddle.grad(y, [x, z], create_graph=True, allow_unused=True)
    np.testing.assert_allclose(gx.numpy(), [2.0])
    assert gz is None


def test_double_grad_under_to_static():
    @paddle.jit.to_static
    def curvature(x):
        y = (x * x * x).sum()
        (g1,) = paddle.grad(y, x, create_graph=True)
        return (g1 * g1).sum()

    x = _leaf([1.0, 2.0])
    out = curvature(x)
    # ||3x^2||^2 = 9 + 144
    np.testing.assert_allclose(float(out.numpy()), 153.0, rtol=1e-5)


def test_input_ancestor_of_input_chain_through():
    """grad(out, [x, y]) where y = f(x): x gets the FULL chain-rule grad
    through y (torch/paddle reference semantics), not a severed zero."""
    x = _leaf([3.0])
    y = x * 2.0
    out = (y * y).sum()
    gx, gy = paddle.grad(out, [x, y], create_graph=True)
    np.testing.assert_allclose(gy.numpy(), [12.0])   # 2y = 12
    np.testing.assert_allclose(gx.numpy(), [24.0])   # d/dx (2x)^2 = 8x
    # and the chain grads stay differentiable
    (gxx,) = paddle.grad(gx.sum(), x)
    np.testing.assert_allclose(gxx.numpy(), [8.0])


def test_pylayer_ancestry_raises_detach_works():
    """A PyLayer (no primal replay fn) in the live ancestry raises a clear
    NotImplementedError; the documented .detach() recipe (the WGAN-GP
    detached-interpolate pattern) works."""
    from paddle_tpu.autograd import PyLayer

    class Triple(PyLayer):
        @staticmethod
        def forward(ctx, a):
            return a * 3.0

        @staticmethod
        def backward(ctx, g):
            return g * 3.0

    base = _leaf(np.ones(4))
    mid = Triple.apply(base)           # PyLayer node in the ancestry
    out = (mid * mid).sum()
    with pytest.raises(NotImplementedError, match="detach"):
        paddle.grad(out, mid, create_graph=True)

    x = mid.detach()                   # the documented recipe
    x.stop_gradient = False
    out2 = (x * x).sum()
    (gx,) = paddle.grad(out2, x, create_graph=True)
    np.testing.assert_allclose(gx.numpy(), 2 * 3 * np.ones(4), rtol=1e-6)


def test_second_order_wrt_nonleaf_input():
    """d(gx)/dy must work when y is a non-leaf input: the grad_replay node
    carries a leaf-like edge to the ORIGINAL y (not a hidden proxy)."""
    x = _leaf([3.0])
    y = x * 2.0
    out = (y * y).sum()
    gx, gy = paddle.grad(out, [x, y], create_graph=True)
    (d_gx_dy,) = paddle.grad(gx, y)      # gx = 4y (as a fn of y) → 4
    np.testing.assert_allclose(d_gx_dy.numpy(), [4.0])


def test_duplicate_inputs():
    x = _leaf([2.0])
    y = (x * x).sum()
    g1, g2 = paddle.grad(y, [x, x], create_graph=True)
    np.testing.assert_allclose(g1.numpy(), [4.0])
    np.testing.assert_allclose(g2.numpy(), [4.0])


def test_numpy_grad_outputs_coerced():
    x = _leaf([1.0, 2.0])
    y = x * x
    (g,) = paddle.grad(y, x, grad_outputs=[np.ones(2)],  # float64 numpy seed
                       create_graph=True)
    np.testing.assert_allclose(g.numpy(), [2.0, 4.0])


def test_freed_graph_clear_error():
    x = _leaf([1.0])
    y = (x * x).sum()
    y.backward()                          # frees vjp_fn AND pure_fn
    with pytest.raises(RuntimeError, match="second time"):
        paddle.grad(y, x, create_graph=True)


def test_get_concrete_program_with_grad():
    @paddle.jit.to_static
    def curvature(x):
        y = (x * x * x).sum()
        (g1,) = paddle.grad(y, x, create_graph=True)
        return (g1 * g1).sum()

    lowered = curvature.get_concrete_program(_leaf([1.0, 2.0]))
    assert lowered is not None


def test_nonleaf_input_grad_not_polluted():
    """backward through create_graph grads must NOT write .grad on a
    non-leaf input (the severed edge is not a leaf edge)."""
    x = _leaf([3.0])
    y = x * 2.0
    out = (y * y).sum()
    gx, gy = paddle.grad(out, [x, y], create_graph=True)
    (gx * gx).sum().backward()
    assert y.grad is None, y.grad
    assert x.grad is not None


def test_first_order_grad_unchanged():
    x = _leaf([1.0, 2.0])
    y = (x * x).sum()
    (g,) = paddle.grad(y, x)
    np.testing.assert_allclose(g.numpy(), [2.0, 4.0])
    assert x.grad is None   # paddle.grad must not write .grad
