"""Long-context sep-parallel serving (ISSUE 19): ring-attention
blockwise prefill over fixed stripes — kernel-tier parity, cache-level
stripe lifecycle, striped disagg handoff, and engine greedy parity
against the single-device oracle for prompts that exceed the device
page pool."""
import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.core import Tensor
from paddle_tpu.inference import ContinuousServingEngine
from paddle_tpu.models import LlamaForCausalLM, llama_tiny
from paddle_tpu.models.generation import HostKVPool, SlotPagedKVCache
from paddle_tpu.ops.pallas.flash_attention import mha_reference
from paddle_tpu.ops.pallas.ring_attention import (
    SEP_RING_IMPLS, blockwise_causal_attention, sep_ring_impl)


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    return LlamaForCausalLM(llama_tiny(num_hidden_layers=2))


def _oracle(model, p, n):
    return np.asarray(model.generate(paddle.to_tensor(p),
                                     max_new_tokens=n)._data)


# ---------------------------------------------------------------------------
# kernel tier: blockwise ring schedule == dense causal reference
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("impl", ["auto", "xla"])
def test_blockwise_matches_dense_reference(impl):
    """Splitting the KV into ring blocks and merging the per-block
    partials with the online-softmax combine reproduces dense causal
    attention — for the kernel tier (interpret-pallas off-TPU) and the
    pure-XLA fallback alike, including a fully-masked future block."""
    rng = np.random.default_rng(0)
    b, h, d = 1, 4, 16
    sq, skv = 8, 32
    q = jnp.asarray(rng.standard_normal((b, h, sq, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, skv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, skv, d)), jnp.float32)
    q_off = 16                      # q rows sit at positions 16..23
    blocks = [(k[:, :, i:i + 8], v[:, :, i:i + 8], i)
              for i in range(0, skv, 8)]     # last block fully masked
    got = blockwise_causal_attention(q, q_off, blocks, impl=impl)
    ref, _ = mha_reference(q, k, v, causal=True,
                           sm_scale=1.0 / np.sqrt(d), q_offset=q_off,
                           kv_offset=0, with_lse=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_impl_env_knob(monkeypatch):
    monkeypatch.setenv("PADDLE_SEP_RING_IMPL", "xla")
    assert sep_ring_impl() == "xla"
    monkeypatch.setenv("PADDLE_SEP_RING_IMPL", "kernel")
    assert sep_ring_impl() == "kernel"
    assert "auto" in SEP_RING_IMPLS
    monkeypatch.setenv("PADDLE_SEP_RING_IMPL", "bogus")
    with pytest.raises(ValueError):
        sep_ring_impl()


# ---------------------------------------------------------------------------
# cache level: stripe lifecycle + striped handoff
# ---------------------------------------------------------------------------

def _mk_sep_cache():
    return SlotPagedKVCache(2, page_size=4, max_len=64, num_pages=9,
                            allow_page_overcommit=True,
                            host_pool=HostKVPool(0))


def _drive_sep(cache, layer, q_all, k_all, v_all, prompt_len, stripe,
               new_tokens):
    """Chunked sep prefill + per-token decode, returning the attention
    outputs for every position (valid rows only)."""
    slot = 0
    cache.assign_sep(slot, prompt_len, stripe)
    outs = []
    pos = 0
    while pos < prompt_len:
        n_valid = min(stripe, prompt_len - pos)
        pad = stripe - n_valid
        pad4 = ((0, 0), (0, pad), (0, 0), (0, 0))
        cache.begin_sep_prefill(slot, n_valid=n_valid)
        o = cache.attend(
            layer,
            Tensor(jnp.asarray(np.pad(q_all[:, pos:pos + n_valid], pad4))),
            Tensor(jnp.asarray(np.pad(k_all[:, pos:pos + n_valid], pad4))),
            Tensor(jnp.asarray(np.pad(v_all[:, pos:pos + n_valid], pad4))))
        outs.append(np.asarray(o._data)[:, :n_valid])
        cache.advance(stripe)
        pos += n_valid
    for t in range(new_tokens):
        p = prompt_len + t
        cache.begin_sep_decode(slot)
        o = cache.attend(layer, Tensor(jnp.asarray(q_all[:, p:p + 1])),
                         Tensor(jnp.asarray(k_all[:, p:p + 1])),
                         Tensor(jnp.asarray(v_all[:, p:p + 1])))
        outs.append(np.asarray(o._data))
        cache.advance(1)
    return np.concatenate(outs, axis=1)


@pytest.mark.parametrize("prompt_len", [21, 24])
def test_sep_cache_matches_dense(prompt_len):
    """Stripe-chunked sep prefill + tail decode equals dense causal
    attention over the whole sequence — with and without a trailing
    partial chunk. The prompt exceeds the 8-usable-page device pool;
    only the tail ever lives in device pages."""
    rng = np.random.default_rng(1)
    h, hk, d, stripe, new = 4, 2, 8, 8, 5
    total = prompt_len + new
    q = rng.standard_normal((1, total, h, d)).astype(np.float32)
    k = rng.standard_normal((1, total, hk, d)).astype(np.float32)
    v = rng.standard_normal((1, total, hk, d)).astype(np.float32)
    cache = _mk_sep_cache()
    got = _drive_sep(cache, object(), q, k, v, prompt_len, stripe, new)
    ref, _ = mha_reference(jnp.swapaxes(jnp.asarray(q), 1, 2),
                           jnp.swapaxes(jnp.asarray(k), 1, 2),
                           jnp.swapaxes(jnp.asarray(v), 1, 2),
                           causal=True, sm_scale=1.0 / np.sqrt(d),
                           with_lse=True)
    ref = np.asarray(jnp.swapaxes(ref, 1, 2))
    np.testing.assert_allclose(got, ref, atol=2e-5, rtol=2e-5)
    assert cache.sep_stripes_stored == prompt_len // stripe
    assert cache.sep_decode_steps == new
    view = cache.sep_view(0)
    assert view["stripes"] == prompt_len // stripe
    assert view["len"] == prompt_len            # the admitted span
    assert int(cache.lens[0]) == prompt_len + new


def test_striped_handoff_continues_bit_exact(monkeypatch):
    """export_stripes -> import_stripes onto a second cache mid-decode:
    stripes carry their sep-way home tags (PADDLE_SEP_WAYS striping) and
    the next decoded token's attention is bit-identical."""
    monkeypatch.setenv("PADDLE_SEP_WAYS", "4")
    rng = np.random.default_rng(2)
    h, hk, d, stripe, plen, new = 4, 2, 8, 8, 21, 5
    total = plen + new + 1
    layer = object()
    q = rng.standard_normal((1, total, h, d)).astype(np.float32)
    k = rng.standard_normal((1, total, hk, d)).astype(np.float32)
    v = rng.standard_normal((1, total, hk, d)).astype(np.float32)
    src = _mk_sep_cache()
    _drive_sep(src, layer, q, k, v, plen, stripe, new)
    blob = src.export_stripes(0)
    assert [st["home"] for st in blob["stripes"]] == \
        [j % 4 for j in range(len(blob["stripes"]))]
    assert blob["tail"] is not None          # mid-span decode state

    dst = _mk_sep_cache()
    # materialize dst pools with a scratch stripe, then import
    dst.assign_sep(1, 4, stripe)
    dst.begin_sep_prefill(1, n_valid=4)
    z = np.zeros((1, stripe, hk, d), np.float32)
    dst.attend(layer, Tensor(jnp.asarray(
        np.zeros((1, stripe, h, d), np.float32))),
        Tensor(jnp.asarray(z)), Tensor(jnp.asarray(z)))
    dst.advance(stripe)
    dst.free(1)
    assert dst.import_stripes(0, blob) == len(blob["stripes"])

    p = plen + new
    outs = []
    for cache in (src, dst):
        cache.begin_sep_decode(0)
        o = cache.attend(layer, Tensor(jnp.asarray(q[:, p:p + 1])),
                         Tensor(jnp.asarray(k[:, p:p + 1])),
                         Tensor(jnp.asarray(v[:, p:p + 1])))
        outs.append(np.asarray(o._data))
    np.testing.assert_array_equal(outs[0], outs[1])


def test_sep_validation():
    cache = _mk_sep_cache()
    with pytest.raises(ValueError):          # stripe % page_size != 0
        cache.assign_sep(0, 20, 6)
    with pytest.raises(ValueError):          # prompt > max_len
        cache.assign_sep(0, 100, 8)
    qcache = SlotPagedKVCache(1, page_size=4, max_len=32, num_pages=9,
                              kv_dtype="int8",
                              allow_page_overcommit=True)
    with pytest.raises(ValueError):          # int8 pools are paged-only
        qcache.assign_sep(0, 20, 8)


# ---------------------------------------------------------------------------
# engine level: long-context greedy parity vs the single-device oracle
# ---------------------------------------------------------------------------

def test_engine_long_context_parity(model):
    """A 100-token prompt against a 15-usable-page (60-token) device
    pool: inadmissible via the paged path, served by sep-ring prefill
    with greedy output bit-identical to the dense oracle. A short prompt
    on the same config still takes the paged path."""
    rng = np.random.RandomState(7)
    prompt = rng.randint(0, 128, (1, 100)).astype(np.int64)
    short = rng.randint(0, 128, (1, 6)).astype(np.int64)
    want = _oracle(model, prompt, 8)
    want_s = _oracle(model, short, 4)
    eng = ContinuousServingEngine(model, max_batch_size=2, page_size=4,
                                  max_len=256, num_pages=16,
                                  sep_prefill=True, sep_stripe_tokens=16)
    assert prompt.shape[1] > (16 - 1) * 4    # exceeds the device pool
    with eng:
        got = np.asarray(eng.generate(prompt, max_new_tokens=8,
                                      timeout=300).numpy())
        got_s = np.asarray(eng.generate(short, max_new_tokens=4,
                                        timeout=300).numpy())
    np.testing.assert_array_equal(got, want)
    np.testing.assert_array_equal(got_s, want_s)
    assert eng.sep_requests == 1             # only the long prompt
    assert eng._cache.sep_stripes_stored >= 100 // 16
    assert eng._cache.sep_chunks == -(-100 // 16)


def test_engine_env_knobs_and_validation(model, monkeypatch):
    monkeypatch.setenv("PADDLE_SEP_PREFILL", "1")
    monkeypatch.setenv("PADDLE_SEP_STRIPE_TOKENS", "32")
    monkeypatch.setenv("PADDLE_SEP_THRESHOLD_TOKENS", "77")
    eng = ContinuousServingEngine(model, page_size=16)
    assert eng.sep_prefill_enabled
    assert eng.sep_stripe == 32
    assert eng.sep_threshold == 77
    # declared observatory families for the new program shapes
    from paddle_tpu.profiler import compile_observatory as co
    try:
        co.enable()
        co.reset()
        eng2 = ContinuousServingEngine(model, page_size=16,
                                       host_pool_mb=8)
        fams = set(co.declared_families())
        assert {"serving.sep_prefill", "serving.sep_decode",
                "kv.host_promote"} <= fams
        assert eng2.sep_prefill_enabled
    finally:
        co.disable()
        co.reset()
    # stripe must be a positive multiple of page_size
    monkeypatch.setenv("PADDLE_SEP_STRIPE_TOKENS", "30")
    with pytest.raises(ValueError):
        ContinuousServingEngine(model, page_size=16)
    # sep needs the ragged scheduler
    monkeypatch.setenv("PADDLE_SEP_STRIPE_TOKENS", "32")
    with pytest.raises(ValueError):
        ContinuousServingEngine(model, page_size=16, enable_ragged=False)
    # int8 KV pools can't back the ring schedule
    with pytest.raises(ValueError):
        ContinuousServingEngine(model, page_size=16, kv_dtype="int8")
    monkeypatch.delenv("PADDLE_SEP_PREFILL")
    assert not ContinuousServingEngine(model,
                                       page_size=16).sep_prefill_enabled
