"""Heterogeneous pipeline stages (VERDICT round-4 item 7; reference:
``pp_utils/p2p_communication.py`` negotiates per-stage recv shapes via a
tensor-meta exchange, so stages with different widths/params pipeline
fine). ``pipeline_forward_hetero`` gives the SPMD engine the same
freedom: per-stage bodies picked by ``lax.switch``, per-stage param
leaves slot-packed/zero-padded into one shardable stack, activations
padded to the max wire shape INSIDE the engine (not by the caller), for
all three backward schedules."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.engine import pipeline_forward_hetero
from conftest import requires_spmd_pipeline


def _mk(rng, i, o, extra=False):
    p = {"w": jnp.asarray(rng.normal(size=(i, o)) * 0.4, jnp.float32),
         "b": jnp.asarray(rng.normal(size=(o,)) * 0.1, jnp.float32)}
    if extra:
        p["g"] = jnp.asarray(rng.normal(size=(o,)) * 0.05, jnp.float32)
    return p


def _f_plain(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _f_extra(p, x):
    return jnp.tanh(x @ p["w"] + p["b"]) * (1 + p["g"])


def _setup():
    rng = np.random.default_rng(0)
    # widths 8 -> 12 -> 16 -> 12 -> 8; stage 1 has an extra leaf the
    # others lack (different param SIGNATURES, not just shapes)
    widths = [(8, 12), (12, 16), (16, 12), (12, 8)]
    params = [_mk(rng, *widths[0]), _mk(rng, *widths[1], extra=True),
              _mk(rng, *widths[2]), _mk(rng, *widths[3])]
    fns = [_f_plain, _f_extra, _f_plain, _f_plain]
    micro = jnp.asarray(rng.normal(size=(4, 2, 8)), jnp.float32)
    g = jnp.asarray(rng.normal(size=(4, 2, 8)), jnp.float32)
    return fns, params, micro, g


def _seq(fns, ps, x):
    outs = []
    for m in range(x.shape[0]):
        h = x[m]
        for s in range(len(fns)):
            h = fns[s](ps[s], h)
        outs.append(h)
    return jnp.stack(outs)


@pytest.mark.parametrize("sched", ["fthenb", "1f1b", "zb"])
@requires_spmd_pipeline
def test_hetero_stage_widths_parity(sched):
    fns, params, micro, g = _setup()
    o_ref = _seq(fns, params, micro)
    go_ref = jax.grad(lambda ps: jnp.sum(_seq(fns, ps, micro) * g))(params)
    mesh_mod.init_mesh({"pp": 4, "dp": 2})
    try:
        out = jax.jit(lambda ps, x: pipeline_forward_hetero(
            fns, ps, x, schedule=sched))(params, micro)
        np.testing.assert_allclose(np.asarray(out), np.asarray(o_ref),
                                   rtol=1e-5, atol=1e-5)
        gp = jax.jit(jax.grad(lambda ps: jnp.sum(pipeline_forward_hetero(
            fns, ps, micro, schedule=sched) * g)))(params)
        for a, b in zip(jax.tree.leaves(gp), jax.tree.leaves(go_ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)
    finally:
        mesh_mod.reset_mesh()


@requires_spmd_pipeline
def test_hetero_layer_stages_parity():
    """A Pipe-style model built from REAL Layers with per-stage widths:
    embedding-ish widening stage, two different-width MLP stages, and a
    narrowing head stage — through FunctionalModule per stage."""
    from paddle_tpu import nn
    from paddle_tpu.framework.functional import FunctionalModule

    paddle.seed(3)
    stages = [
        nn.Sequential(nn.Linear(8, 24), nn.GELU()),
        nn.Sequential(nn.Linear(24, 32), nn.GELU(), nn.Linear(32, 24)),
        nn.Sequential(nn.LayerNorm(24), nn.Linear(24, 16)),
        nn.Sequential(nn.Linear(16, 8)),
    ]
    fms = [FunctionalModule(s) for s in stages]
    params = [fm.param_arrays() for fm in fms]
    key = jax.random.PRNGKey(0)
    fns = [lambda p, x, fm=fm: fm(p, [], key, x)[0] for fm in fms]

    rng = np.random.default_rng(5)
    micro = jnp.asarray(rng.normal(size=(4, 2, 8)), jnp.float32)
    g = jnp.asarray(rng.normal(size=(4, 2, 8)), jnp.float32)
    o_ref = _seq(fns, params, micro)
    go_ref = jax.grad(lambda ps: jnp.sum(_seq(fns, ps, micro) * g))(params)

    mesh_mod.init_mesh({"pp": 4, "dp": 2})
    try:
        for sched in ("fthenb", "1f1b"):
            out = jax.jit(lambda ps, x: pipeline_forward_hetero(
                fns, ps, x, schedule=sched))(params, micro)
            np.testing.assert_allclose(np.asarray(out), np.asarray(o_ref),
                                       rtol=1e-5, atol=1e-5, err_msg=sched)
            gp = jax.jit(jax.grad(lambda ps: jnp.sum(pipeline_forward_hetero(
                fns, ps, micro, schedule=sched) * g)))(params)
            for a, b in zip(jax.tree.leaves(gp), jax.tree.leaves(go_ref)):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-4, atol=1e-5,
                                           err_msg=sched)
    finally:
        mesh_mod.reset_mesh()


@requires_spmd_pipeline
def test_hetero_dropout_keys():
    """Stochastic hetero stages reproduce the sequential run given the
    same base key (per-(micro, stage) key threading)."""
    from paddle_tpu.distributed.engine import _chunk_key

    rng = np.random.default_rng(2)
    params = [_mk(rng, 8, 16), _mk(rng, 16, 8)]

    def s0(p, x, key):
        keep = jax.random.bernoulli(key, 0.8, (x.shape[0], 16))
        return jnp.tanh(x @ p["w"] + p["b"]) * keep

    def s1(p, x, key):
        return jnp.tanh(x @ p["w"] + p["b"])

    fns = [s0, s1]
    micro = jnp.asarray(rng.normal(size=(4, 2, 8)), jnp.float32)
    base = jax.random.key(11)
    g = jnp.asarray(rng.normal(size=(4, 2, 8)), jnp.float32)

    def seq(ps):
        outs = []
        for m in range(micro.shape[0]):
            h = micro[m]
            for s in range(2):
                h = fns[s](ps[s], h, _chunk_key(base, m, s))
            outs.append(h)
        return jnp.stack(outs)

    mesh_mod.init_mesh({"pp": 2, "dp": 4})
    try:
        gp = jax.jit(jax.grad(lambda ps: jnp.sum(pipeline_forward_hetero(
            fns, ps, micro, rng_key=base, schedule="1f1b") * g)))(params)
        gs = jax.grad(lambda ps: jnp.sum(seq(ps) * g))(params)
        for a, b in zip(jax.tree.leaves(gp), jax.tree.leaves(gs)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)
    finally:
        mesh_mod.reset_mesh()
