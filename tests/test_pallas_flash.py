"""Pallas flash-attention kernel + ring attention (CP) tests.

Run on CPU in interpret mode (conftest forces an 8-device CPU backend);
numeric oracle is the pure-XLA ``mha_reference`` / a global-attention run.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas import (
    flash_attention, flash_attention_with_lse, mha_reference,
    ring_flash_attention,
)


def _rand(shape, seed=0, dtype=np.float32):
    return jnp.asarray(np.random.default_rng(seed).normal(size=shape), dtype)


def _mk(b=1, h=2, s=128, d=32, hk=None, seed=0):
    hk = hk or h
    q = _rand((b, h, s, d), seed)
    k = _rand((b, hk, s, d), seed + 1)
    v = _rand((b, hk, s, d), seed + 2)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("s,block", [(128, 64), (96, 64)])
def test_fwd_matches_reference(causal, s, block):
    q, k, v = _mk(s=s)
    ref = mha_reference(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=block, block_k=block,
                          interpret=True, kernel_layout=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_fwd_gqa_and_lse():
    q, k, v = _mk(h=4, hk=2, s=128, d=16)
    ref, ref_lse = mha_reference(q, k, v, causal=True, with_lse=True)
    out, lse = flash_attention_with_lse(q, k, v, causal=True, block_q=64,
                                        block_k=64, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse),
                               rtol=2e-4, atol=2e-4)


def test_offsets_mask_globally():
    # Q shard [64:128) of a 128-seq vs full KV == rows [64:128) of global attn
    qg, kg, vg = _mk(s=128, d=16, seed=3)
    ref = mha_reference(qg, kg, vg, causal=True)
    out = flash_attention(qg[:, :, 64:], kg, vg, causal=True, q_offset=64,
                          kv_offset=0, block_q=64, block_k=64, interpret=True,
                          kernel_layout=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref[:, :, 64:]),
                               rtol=2e-4, atol=2e-4)
    # fully-masked (KV strictly in the future): zero output
    out2, lse2 = flash_attention_with_lse(
        qg[:, :, :64], kg[:, :, 64:], vg[:, :, 64:], causal=True,
        q_offset=0, kv_offset=64, block_q=64, block_k=64, interpret=True)
    assert np.abs(np.asarray(out2)).max() == 0.0
    assert np.asarray(lse2).max() < -1e29


@pytest.mark.parametrize("causal", [True, False])
def test_grads_match_reference(causal):
    q, k, v = _mk(b=1, h=2, s=96, d=16, seed=5)
    g = _rand(q.shape, 9)

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32,
                              interpret=True, kernel_layout=True)
        return jnp.sum(out * g)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=causal) * g)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)


def test_grads_gqa():
    q, k, v = _mk(b=1, h=4, hk=2, s=64, d=16, seed=7)
    g = _rand(q.shape, 11)

    def loss(fn):
        def f(q, k, v):
            return jnp.sum(fn(q, k, v) * g)
        return f

    gf = jax.grad(loss(lambda q, k, v: flash_attention(
        q, k, v, causal=True, block_q=32, block_k=32, interpret=True,
        kernel_layout=True)), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss(lambda q, k, v: mha_reference(q, k, v, causal=True)),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)


# ---------------------------------------------------------------------------
# Ring attention over the sep axis
# ---------------------------------------------------------------------------

def _ring_setup(n=4, b=1, h=2, s=256, d=16, hk=None):
    import functools
    from jax.sharding import Mesh, PartitionSpec as P
    # check_vma=False: pallas_call inside shard_map needs explicit vma otherwise
    shard_map = functools.partial(jax.shard_map, check_vma=False)
    devs = np.array(jax.devices()[:n])
    mesh = Mesh(devs, ("sep",))
    q = _rand((b, s, h, d), 21)          # paddle layout [b, s, h, d]
    k = _rand((b, s, hk or h, d), 22)
    v = _rand((b, s, hk or h, d), 23)
    return mesh, P, shard_map, q, k, v


@pytest.mark.parametrize("use_kernel", [False, True])
def test_ring_matches_global(use_kernel):
    n = 4
    mesh, P, shard_map, q, k, v = _ring_setup(n=n)
    spec = P(None, "sep", None, None)

    def fn(q, k, v):
        return ring_flash_attention(q, k, v, axis_name="sep", causal=True,
                                    axis_size=n, interpret=True,
                                    use_kernel=use_kernel)

    out = jax.jit(shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                            out_specs=spec))(q, k, v)
    ref = mha_reference(jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
                        jnp.swapaxes(v, 1, 2), causal=True)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(jnp.swapaxes(ref, 1, 2)),
                               rtol=3e-4, atol=3e-4)


def test_ring_grad_matches_global():
    n = 2
    mesh, P, shard_map, q, k, v = _ring_setup(n=n, s=128, h=2, hk=1)
    spec = P(None, "sep", None, None)
    g = _rand(q.shape, 31)

    ring = shard_map(
        lambda q, k, v: ring_flash_attention(
            q, k, v, axis_name="sep", causal=True, axis_size=n,
            interpret=True),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)

    def loss_ring(q, k, v):
        return jnp.sum(ring(q, k, v) * g)

    def loss_ref(q, k, v):
        out = mha_reference(jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
                            jnp.swapaxes(v, 1, 2), causal=True)
        return jnp.sum(jnp.swapaxes(out, 1, 2) * g)

    gr_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    gr_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr_ring, gr_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3)


def test_ring_attention_in_hybrid_mesh():
    """User-level ring_attention under jit on a dp×sep mesh (other axes auto)."""
    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.distributed.fleet.utils import ring_attention
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = mesh_mod.init_mesh({"dp": 2, "sep": 4})
    try:
        q = _rand((2, 256, 2, 16), 41)
        k = _rand((2, 256, 2, 16), 42)
        v = _rand((2, 256, 2, 16), 43)
        shard = NamedSharding(mesh, P("dp", "sep", None, None))
        qs, ks, vs = (jax.device_put(x, shard) for x in (q, k, v))

        fn = jax.jit(lambda q, k, v: ring_attention(
            q, k, v, causal=True, interpret=True))
        out = fn(qs, ks, vs)
        ref = mha_reference(jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
                            jnp.swapaxes(v, 1, 2), causal=True)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(jnp.swapaxes(ref, 1, 2)),
                                   rtol=3e-4, atol=3e-4)
    finally:
        mesh_mod.reset_mesh()


class TestXlaFlashTier:
    """Pure-XLA flash tier (_xflash): the training path for zero-Mosaic
    sessions (rounds 2-4 tunnel wedge). Parity vs mha_reference with
    multi-block scans forced via the block-size env knobs."""

    def _check(self, b, hq, hk, sq, sk, d, causal, qo, ko, monkeypatch):
        import jax
        import jax.numpy as jnp
        monkeypatch.setenv("PADDLE_TPU_XFA_BLOCK_Q", "64")
        monkeypatch.setenv("PADDLE_TPU_XFA_BLOCK_K", "32")
        from paddle_tpu.ops.pallas.flash_attention import (
            NEG_INF, _xflash, _xflash_with_lse, mha_reference)
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.standard_normal((b, hq, sq, d)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((b, hk, sk, d)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((b, hk, sk, d)), jnp.float32)
        offs = jnp.asarray([qo, ko], jnp.int32)
        out, lse = jax.jit(
            lambda *a: _xflash_with_lse(*a, causal, 0.125))(q, k, v, offs)
        ref, rlse = mha_reference(q, k, v, causal=causal, sm_scale=0.125,
                                  q_offset=qo, kv_offset=ko, with_lse=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)
        live = np.asarray(rlse) > NEG_INF / 2
        np.testing.assert_allclose(np.asarray(lse)[live],
                                   np.asarray(rlse)[live], atol=2e-5)

        def loss_x(q, k, v):
            return (_xflash(q, k, v, offs, causal, 0.125) ** 2).sum()

        def loss_r(q, k, v):
            return (mha_reference(q, k, v, causal=causal, sm_scale=0.125,
                                  q_offset=qo, kv_offset=ko) ** 2).sum()

        gx = jax.jit(jax.grad(loss_x, (0, 1, 2)))(q, k, v)
        gr = jax.grad(loss_r, (0, 1, 2))(q, k, v)
        for a, b_ in zip(gx, gr):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b_), atol=5e-4)

    def test_causal_mha(self, monkeypatch):
        self._check(2, 4, 4, 128, 128, 32, True, 0, 0, monkeypatch)

    def test_causal_gqa_uneven(self, monkeypatch):
        self._check(2, 8, 2, 128, 96, 32, True, 0, 0, monkeypatch)

    def test_full_attention(self, monkeypatch):
        self._check(2, 4, 4, 128, 128, 32, False, 0, 0, monkeypatch)

    def test_decode_offset(self, monkeypatch):
        self._check(1, 4, 2, 64, 256, 32, True, 192, 0, monkeypatch)

    def test_fully_masked_rows(self, monkeypatch):
        self._check(1, 2, 2, 64, 64, 16, True, 0, 32, monkeypatch)

    def test_lse_cotangent_flows(self, monkeypatch):
        """Ring attention differentiates through lse (shard merging) — the
        XLA tier must propagate the lse cotangent like the Mosaic bwd."""
        import jax
        import jax.numpy as jnp
        monkeypatch.setenv("PADDLE_TPU_XFA_BLOCK_Q", "32")
        monkeypatch.setenv("PADDLE_TPU_XFA_BLOCK_K", "32")
        from paddle_tpu.ops.pallas.flash_attention import (
            _xflash_with_lse, mha_reference)
        rng = np.random.default_rng(2)
        q = jnp.asarray(rng.standard_normal((1, 2, 64, 16)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((1, 2, 64, 16)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((1, 2, 64, 16)), jnp.float32)
        offs = jnp.asarray([0, 0], jnp.int32)

        def loss_x(q, k, v):
            out, lse = _xflash_with_lse(q, k, v, offs, True, 0.25)
            return (out ** 2).sum() + (lse * 0.3).sum()

        def loss_r(q, k, v):
            out, lse = mha_reference(q, k, v, causal=True, sm_scale=0.25,
                                     with_lse=True)
            return (out ** 2).sum() + (lse * 0.3).sum()

        gx = jax.jit(jax.grad(loss_x, (0, 1, 2)))(q, k, v)
        gr = jax.grad(loss_r, (0, 1, 2))(q, k, v)
        for a, b in zip(gx, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4)


class TestChunkedFallbackTier:
    """The chunked-reference tier (_xla_fallback with sq > chunk) — the
    path long sequences take when the scan formulation is pinned off
    (PADDLE_TPU_XFA=0, added after the round-4 remote-compile wedge)."""

    def test_chunked_matches_unchunked(self):
        import jax.numpy as jnp
        from paddle_tpu.ops.pallas.flash_attention import (_xla_fallback,
                                                           mha_reference)
        rng = np.random.default_rng(3)
        q = jnp.asarray(rng.standard_normal((1, 2, 256, 16)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((1, 2, 256, 16)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((1, 2, 256, 16)), jnp.float32)
        out = _xla_fallback(q, k, v, True, 0.25, 0, 0, chunk=64)
        ref = mha_reference(q, k, v, causal=True, sm_scale=0.25)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)
        o2, l2 = _xla_fallback(q, k, v, True, 0.25, 0, 0, with_lse=True,
                               chunk=64)
        r2, rl2 = mha_reference(q, k, v, causal=True, sm_scale=0.25,
                                with_lse=True)
        np.testing.assert_allclose(np.asarray(o2), np.asarray(r2), atol=2e-5)
        np.testing.assert_allclose(np.asarray(l2), np.asarray(rl2), atol=2e-5)

    def test_chunked_offsets_trimmed_kv(self):
        """Bottom-right-aligned causal (decode convention, q_offset>0):
        the kv-trim must respect global positions, not local indices."""
        import jax.numpy as jnp
        from paddle_tpu.ops.pallas.flash_attention import (_xla_fallback,
                                                           mha_reference)
        rng = np.random.default_rng(5)
        q = jnp.asarray(rng.standard_normal((1, 2, 128, 16)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((1, 2, 256, 16)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((1, 2, 256, 16)), jnp.float32)
        out = _xla_fallback(q, k, v, True, 0.25, 128, 0, chunk=32)
        ref = mha_reference(q, k, v, causal=True, sm_scale=0.25,
                            q_offset=128, kv_offset=0)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_chunked_grads_match(self):
        """The chunk remat (jax.checkpoint per chunk) must not change
        gradients — and grads must flow through k/v, which are shared
        across every chunk call."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.ops.pallas.flash_attention import (_xla_fallback,
                                                           mha_reference)
        rng = np.random.default_rng(4)
        q = jnp.asarray(rng.standard_normal((1, 2, 256, 16)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((1, 2, 256, 16)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((1, 2, 256, 16)), jnp.float32)

        def loss_c(q, k, v):
            out, lse = _xla_fallback(q, k, v, True, 0.25, 0, 0,
                                     with_lse=True, chunk=64)
            return (out ** 2).sum() + (lse * 0.1).sum()

        def loss_r(q, k, v):
            out, lse = mha_reference(q, k, v, causal=True, sm_scale=0.25,
                                     with_lse=True)
            return (out ** 2).sum() + (lse * 0.1).sum()

        gc = jax.jit(jax.grad(loss_c, (0, 1, 2)))(q, k, v)
        gr = jax.grad(loss_r, (0, 1, 2))(q, k, v)
        for a, b in zip(gc, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-5)

    def test_xfa_env_pin_forces_chunked(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_XFA", "0")
        from paddle_tpu.ops.pallas.flash_attention import _xflash_ok
        import jax.numpy as jnp
        q = jnp.zeros((1, 2, 512, 16))
        assert not _xflash_ok(q, q)
        monkeypatch.setenv("PADDLE_TPU_XFA", "1")
        assert _xflash_ok(q, q)


class TestScanQTier:
    """Single-level scan tier (_scanq): lax.scan over q-chunks, full-K
    per chunk, remat body — constant graph size in sequence length, no
    scan-in-scan/custom_vjp (the structures suspected in the round-4
    remote-compile hang)."""

    def _all(self, b, hq, hk, sq, sk, d, causal, qo, chunk):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.ops.pallas.flash_attention import (_scanq,
                                                           mha_reference)
        rng = np.random.default_rng(6)
        q = jnp.asarray(rng.standard_normal((b, hq, sq, d)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((b, hk, sk, d)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((b, hk, sk, d)), jnp.float32)
        out, lse = jax.jit(lambda q, k, v: _scanq(
            q, k, v, causal, 0.25, qo, 0, with_lse=True, chunk=chunk))(
                q, k, v)
        ref, rlse = mha_reference(q, k, v, causal=causal, sm_scale=0.25,
                                  q_offset=qo, with_lse=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)
        np.testing.assert_allclose(np.asarray(lse), np.asarray(rlse),
                                   atol=2e-5)

        def loss_s(q, k, v):
            return (_scanq(q, k, v, causal, 0.25, qo, 0,
                           chunk=chunk) ** 2).sum()

        def loss_r(q, k, v):
            return (mha_reference(q, k, v, causal=causal, sm_scale=0.25,
                                  q_offset=qo) ** 2).sum()

        gs = jax.jit(jax.grad(loss_s, (0, 1, 2)))(q, k, v)
        gr = jax.grad(loss_r, (0, 1, 2))(q, k, v)
        for a, b_ in zip(gs, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=5e-5)

    def test_causal_mha(self):
        self._all(1, 2, 2, 256, 256, 16, True, 0, 64)

    def test_noncausal_gqa(self):
        self._all(1, 4, 2, 128, 128, 16, False, 0, 32)

    def test_decode_aligned_offset(self):
        self._all(1, 2, 2, 128, 256, 16, True, 128, 32)

    def test_selection_knob(self, monkeypatch):
        import importlib
        import jax.numpy as jnp
        # the package re-exports the flash_attention FUNCTION under the
        # same name as the submodule, so plain `import ... as fa` binds
        # the function — load the module object explicitly
        fa = importlib.import_module(
            "paddle_tpu.ops.pallas.flash_attention")
        q = jnp.zeros((1, 2, 2048, 16))
        monkeypatch.setenv("PADDLE_TPU_XFA", "scanq")
        assert fa._scanq_ok(q) and not fa._xflash_ok(q, q)
        monkeypatch.setenv("PADDLE_TPU_XFA", "1")
        assert not fa._scanq_ok(q) and fa._xflash_ok(q, q)
        monkeypatch.setenv("PADDLE_TPU_XFA", "0")
        assert not fa._scanq_ok(q) and not fa._xflash_ok(q, q)


@pytest.mark.parametrize("causal", [True, False])
def test_sdpa_long_seq_routes_chunked(causal, monkeypatch):
    """F.scaled_dot_product_attention: no-mask attention at seq>=4096
    with flash unavailable must route through the pure-XLA tier
    dispatcher (O(chunk*S) memory) and match the full-scores
    reference. A spy asserts the route is actually taken."""
    import importlib
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    fa = importlib.import_module("paddle_tpu.ops.pallas.flash_attention")

    calls = []
    real = fa.xla_attention
    monkeypatch.setattr(fa, "xla_attention",
                        lambda *a, **kw: (calls.append(1), real(*a, **kw))[1])

    rng = np.random.default_rng(8)
    q = paddle.to_tensor(rng.standard_normal((1, 4096, 1, 8)).astype("float32"))
    k = paddle.to_tensor(rng.standard_normal((1, 4096, 1, 8)).astype("float32"))
    v = paddle.to_tensor(rng.standard_normal((1, 4096, 1, 8)).astype("float32"))
    out = F.scaled_dot_product_attention(q, k, v, is_causal=causal)
    assert calls, "long-seq SDPA did not take the xla_attention route"
    ref = fa.mha_reference(jnp.swapaxes(q._data, 1, 2),
                           jnp.swapaxes(k._data, 1, 2),
                           jnp.swapaxes(v._data, 1, 2), causal=causal)
    np.testing.assert_allclose(np.asarray(out._data),
                               np.asarray(jnp.swapaxes(ref, 1, 2)),
                               atol=3e-5)
