"""Donation / aliasing misuse guards (SURVEY.md §5.2 — the TPU
equivalent of the reference's memory sanitizers; VERDICT.md round-2 §5.2
row: 'no donation/aliasing-misuse guard')."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.utils.donation import (DonatedTensorError, assert_no_aliases,
                                       donated_jit, find_aliases)


def test_donated_jit_poisons_inputs():
    import jax.numpy as jnp
    p = [paddle.to_tensor(np.ones((4, 4), np.float32)),
         paddle.to_tensor(np.full((4,), 2.0, np.float32))]

    def step(arrs, x):
        w, b = arrs
        y = x @ w + b
        return [w - 0.1, b - 0.1], y.sum()

    step_j = donated_jit(step, donate_argnums=(0,))
    x = jnp.ones((2, 4), jnp.float32)
    new_arrs, loss = step_j(p, x)
    assert float(loss) == 2 * 4 * (4 + 2)     # 8 entries of value 6
    # the donated Tensors now raise a CLEAR error on any use
    with pytest.raises(DonatedTensorError, match="DONATED"):
        p[0].numpy()
    with pytest.raises(DonatedTensorError, match="rebind"):
        _ = p[1] + 1.0
    # rebinding the returned arrays is the documented fix
    p2 = [paddle.to_tensor(np.asarray(a)) for a in new_arrs]
    np.testing.assert_allclose(np.asarray(p2[0].numpy()),
                               np.full((4, 4), 0.9, np.float32))


def test_find_and_assert_aliases():
    a = paddle.to_tensor(np.zeros(3, np.float32))
    b = paddle.to_tensor(np.zeros(3, np.float32))
    c = paddle.Tensor(a._data)            # aliases a's buffer
    groups = find_aliases([a, b, c], names=["a", "b", "c"])
    assert groups == [["a", "c"]]
    with pytest.raises(AssertionError, match="aliasing"):
        assert_no_aliases([a, b, c])


def test_assert_no_aliases_on_layers():
    lin = nn.Linear(4, 4)
    assert assert_no_aliases(lin) == []   # clean model: no groups

    class Tied(nn.Layer):
        def __init__(self):
            super().__init__()
            self.embed = nn.Embedding(8, 4)
            self.head = nn.Linear(4, 8, bias_attr=False)
            # two DISTINCT Parameter objects, one backing buffer — the
            # accidental-aliasing shape named_parameters' identity memo
            # cannot dedupe (a same-object tie is deduped there and is
            # not an aliasing hazard)
            self.head.weight._data = self.embed.weight._data

    tied = Tied()
    with pytest.raises(AssertionError):
        assert_no_aliases(tied)
    groups = assert_no_aliases(tied, allow=("embed",))
    assert len(groups) == 1               # reported but allowed
