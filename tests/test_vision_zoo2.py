"""Vision zoo batch 2: forward shapes + one train step per family
(reference test pattern: test/legacy_test/test_vision_models.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import models as M

CASES = [
    ("alexnet", lambda: M.alexnet(num_classes=4), 64),
    ("squeezenet1_0", lambda: M.squeezenet1_0(num_classes=4), 64),
    ("squeezenet1_1", lambda: M.squeezenet1_1(num_classes=4), 64),
    ("mobilenet_v3_small",
     lambda: M.mobilenet_v3_small(num_classes=4), 32),
    ("mobilenet_v3_large",
     lambda: M.mobilenet_v3_large(num_classes=4), 32),
    ("shufflenet_v2_x1_0",
     lambda: M.shufflenet_v2_x1_0(num_classes=4), 32),
    ("densenet121", lambda: M.densenet121(num_classes=4), 32),
    ("wide_resnet50_2", lambda: M.wide_resnet50_2(num_classes=4), 32),
]


@pytest.mark.parametrize("name,mk,size", CASES, ids=[c[0] for c in CASES])
def test_forward_and_train_step(name, mk, size):
    paddle.seed(0)
    model = mk()
    model.train()
    x = paddle.to_tensor(
        np.random.RandomState(0).rand(2, 3, size, size).astype("float32"))
    y = paddle.to_tensor(np.array([1, 3], np.int64))
    logits = model(x)
    assert tuple(logits.shape) == (2, 4), name
    loss = paddle.nn.CrossEntropyLoss()(logits, y)
    loss.backward()
    grads = [p.grad for p in model.parameters() if p.grad is not None]
    assert grads, name
    total = sum(float(np.abs(np.asarray(g.numpy())).sum()) for g in grads)
    assert np.isfinite(total) and total > 0, name


def test_googlenet_aux_heads():
    paddle.seed(0)
    m = M.googlenet(num_classes=4)
    x = paddle.to_tensor(
        np.random.RandomState(0).rand(2, 3, 64, 64).astype("float32"))
    m.train()
    out, aux1, aux2 = m(x)
    assert tuple(out.shape) == (2, 4)
    assert tuple(aux1.shape) == (2, 4) and tuple(aux2.shape) == (2, 4)
    loss = (paddle.nn.CrossEntropyLoss()(out, paddle.to_tensor([0, 1]))
            + 0.3 * paddle.nn.CrossEntropyLoss()(aux1,
                                                 paddle.to_tensor([0, 1])))
    loss.backward()
    m.eval()
    single = m(x)
    assert tuple(single.shape) == (2, 4)


def test_inception_v3_forward():
    paddle.seed(0)
    m = M.inception_v3(num_classes=4)
    m.eval()
    x = paddle.to_tensor(
        np.random.RandomState(0).rand(1, 3, 96, 96).astype("float32"))
    out = m(x)
    assert tuple(out.shape) == (1, 4)


def test_resnext_variants():
    paddle.seed(0)
    for mk in (M.resnext101_32x4d, M.wide_resnet101_2):
        m = mk(num_classes=3)
        m.eval()
        x = paddle.to_tensor(
            np.random.RandomState(0).rand(1, 3, 32, 32).astype("float32"))
        assert tuple(m(x).shape) == (1, 3)


def test_vit_forward_train_and_overfit():
    """ViT family (PaddleClas vision_transformer): cls-token head,
    static sequence, trains to overfit a tiny batch."""
    paddle.seed(0)
    m = M.vit_small_patch16_224(img_size=32, patch_size=8, num_classes=3,
                                depth=2, dropout=0.1)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.rand(4, 3, 32, 32).astype("float32"))
    y = paddle.to_tensor(np.array([0, 1, 2, 0]))
    m.eval()
    out = m(x)
    assert tuple(out.shape) == (4, 3)
    # features: 1 cls + (32/8)^2 patches
    feats = m.forward_features(x)
    assert tuple(feats.shape) == (4, 17, 384)
    # dropout is live in train mode (stochastic forward)
    m.train()
    a = np.asarray(m(x).numpy())
    b = np.asarray(m(x).numpy())
    assert not np.allclose(a, b)

    # overfit check without dropout noise, on a learnable task (pure
    # noise images barely separate through 2 blocks in a few steps):
    # each class gets a distinct channel-mean signature
    sig = np.zeros((3, 3, 1, 1), np.float32)
    sig[0, 0] = 1.5
    sig[1, 1] = 1.5
    sig[2, 2] = 1.5
    xs = rng.rand(4, 3, 32, 32).astype("float32") + sig[[0, 1, 2, 0]]
    xc = paddle.to_tensor(xs)
    m2 = M.vit_small_patch16_224(img_size=32, patch_size=8, num_classes=3,
                                 depth=2)
    m2.train()
    opt = paddle.optimizer.AdamW(learning_rate=2e-3,
                                 parameters=m2.parameters())
    ce = paddle.nn.CrossEntropyLoss()
    losses = []
    for _ in range(40):
        loss = ce(m2(xc), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_vit_variants_constructable():
    for mk, dim in ((M.vit_base_patch16_224, 768),
                    (M.vit_large_patch16_224, 1024)):
        m = mk(img_size=16, patch_size=16, num_classes=2, depth=1)
        assert m.embed_dim == dim
        x = paddle.to_tensor(np.zeros((1, 3, 16, 16), np.float32))
        assert tuple(m(x).shape) == (1, 2)
