"""Auto-parallel Engine + Llama recompute tests."""
import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.auto_parallel import Engine
from paddle_tpu.models import LlamaForCausalLM, llama_tiny
from paddle_tpu.framework.functional import FunctionalModule


def test_engine_fit_linear():
    mesh_mod.init_mesh({"dp": 8})
    try:
        paddle.seed(0)
        model = paddle.nn.Linear(8, 4)
        loss = paddle.nn.MSELoss()
        opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                     parameters=model.parameters())
        eng = Engine(model=model, loss=loss, optimizer=opt)
        eng.prepare()

        from paddle_tpu.io import TensorDataset
        x = paddle.randn([32, 8])
        y = paddle.randn([32, 4])
        ds = TensorDataset([x, y])
        hist = eng.fit(ds, epochs=2, batch_size=16)
        # two batches alternate; compare same-batch losses across epochs
        assert hist[2] < hist[0] and hist[3] < hist[1]
        # trained params synced back into the eager model
        out = eng.predict(x)
        eager_out = model(x)
        np.testing.assert_allclose(out.numpy(), eager_out.numpy(),
                                   rtol=1e-4, atol=1e-4)
    finally:
        mesh_mod.reset_mesh()


def test_engine_sharded_llama_step():
    """Engine with a model exposing sharding_rules: params land sharded."""
    mesh_mod.init_mesh({"dp": 4, "mp": 2})
    try:
        paddle.seed(1)
        model = LlamaForCausalLM(llama_tiny())
        eng = Engine(model=model,
                     loss=None,
                     optimizer=paddle.optimizer.AdamW(
                         learning_rate=1e-3, parameters=model.parameters()))
        eng.prepare()
        from jax.sharding import PartitionSpec as P
        sharded = [s.spec for s in eng._state["p_sh"]]
        assert any(P("mp", None) == s or "mp" in str(s) for s in sharded)
    finally:
        mesh_mod.reset_mesh()


def test_llama_recompute_same_loss_and_grads():
    paddle.seed(2)
    cfg_plain = llama_tiny(use_recompute=False)
    model = LlamaForCausalLM(cfg_plain)
    fm = FunctionalModule(model, training=True)
    p = fm.param_arrays()
    key = fm.next_key()
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 128, (2, 16)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, 128, (2, 16)), jnp.int32)

    def loss_fn(ps):
        (loss, _), _ = fm(ps, [], key, ids, labels=labels)
        return loss

    l0, g0 = jax.jit(jax.value_and_grad(loss_fn))(p)

    model.config.use_recompute = True    # same weights, remat on
    l1, g1 = jax.jit(jax.value_and_grad(loss_fn))(p)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-5)
    for a, b in zip(g0, g1):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)
