"""Self-healing fleet control plane (ISSUE 14).

Unit tier: supervision restart + exponential backoff + circuit-breaker
quarantine (with the page alert), autoscale up/down hysteresis, role
flipping, tenant shedding + restore, env knobs, the requeue budget and
empty-fleet fast-fail satellites, and the fleet fault directives
applied through the router.

Acceptance: a seeded 10x bursty replay with ``kill:replica=...`` firing
mid-run — controller-on recovers (burn alert fires then clears, the
dead replica is restarted), every stream is delivered exactly once and
bit-identical to an undisturbed oracle, and ``fleet_time_to_recover_s``
is finite and lower than the controller-off run on the same seed.
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import fault
from paddle_tpu.distributed.fleet.elastic.tcp_kv import MemKVStore
from paddle_tpu.inference import (ContinuousServingEngine, FleetController,
                                  ServingRouter)
from paddle_tpu.inference.fleet import (CONTROLLER_ACTIONS,
                                        REJECTION_REASONS, Rejected,
                                        replay)
from paddle_tpu.profiler import alerts, request_trace as rt
from paddle_tpu.profiler.telemetry import MetricRegistry, get_registry
from paddle_tpu.profiler.timeseries import MetricsHistory

ENGINE_KW = dict(max_batch_size=4, max_len=160, page_size=16,
                 prefill_chunk_tokens=32)


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    return LlamaForCausalLM(llama_tiny(num_hidden_layers=1,
                                       max_position_embeddings=256))


@pytest.fixture(autouse=True)
def _clean_fault_plan():
    fault.clear()
    yield
    fault.clear()


def _private_history():
    return MetricsHistory(capacity=256, registry=MetricRegistry())


def _router(model, n=2, **kw):
    kw.setdefault("engine_kwargs", ENGINE_KW)
    kw.setdefault("store", MemKVStore())
    kw.setdefault("heartbeat_ttl", 60.0)
    return ServingRouter(model, num_replicas=n, **kw)


def _wait_engine_down(router, rid, timeout=5.0):
    """Let a killed replica's abort finish winding down its serve loop
    (the controller's own guard skips a winding-down engine; tests step
    deterministically so they wait here instead)."""
    eng = router._replica(rid).engine
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        th = getattr(eng, "_thread", None)
        if th is None or not th.is_alive():
            return
        time.sleep(0.02)
    raise TimeoutError(f"replica {rid} engine never stopped")


# ---------------------------------------------------------------------------
# supervision: restart, backoff, circuit breaker
# ---------------------------------------------------------------------------

def test_controller_restart_backoff_and_breaker_page(model):
    """A replica that dies is restarted behind an exponential backoff;
    the third death inside the window trips the breaker — quarantine +
    page-severity alert, never a restart loop — and release() is the
    operator reset."""
    router = _router(model)
    hist = MetricsHistory(capacity=256)         # samples GLOBAL registry
    engine = alerts.AlertEngine(history=hist)
    with router:
        ctl = FleetController(router, history=hist, alert_engine=engine,
                              cooldown_s=0.0, restart_backoff_s=0.5,
                              breaker_n=3, breaker_window_s=60.0,
                              min_replicas=2, down_idle_s=1e6)
        # the breaker's page rule registered itself on the shared engine
        assert "controller_quarantine" in engine.rules
        assert engine.rules["controller_quarantine"].severity == "page"
        now = 100.0
        for strike in (1, 2):
            router.kill_replica("r1")
            _wait_engine_down(router, "r1")
            acts = ctl.step(now=now)            # death observed
            assert not any(a.action == "restart" for a in acts)
            # exponential backoff: 0.5 * 2^(strike-1) before restart
            backoff = 0.5 * (2 ** (strike - 1))
            acts = ctl.step(now=now + backoff / 2)
            assert not any(a.action == "restart" for a in acts), \
                "restarted inside the backoff window"
            acts = ctl.step(now=now + backoff + 0.01)
            assert [a.action for a in acts] == ["restart"]
            assert acts[0].target == "r1"
            assert router._replica("r1").alive
            now += 10.0
        # third death inside the window: quarantine, no restart, page
        router.kill_replica("r1")
        _wait_engine_down(router, "r1")
        acts = ctl.step(now=now)
        assert [a.action for a in acts] == ["quarantine"]
        assert acts[0].reason == "breaker_tripped"
        snap = get_registry().collect()
        assert snap["paddle_controller_quarantined_replicas"][
            "series"][""] == 1
        # the page fires on the next history tick
        hist.tick(now=now)
        engine.evaluate(now=now)
        assert "controller_quarantine" in engine.active
        assert engine.active["controller_quarantine"]["severity"] == "page"
        # quarantined forever: no restart at any later time
        for dt in (1.0, 10.0, 100.0):
            assert ctl.step(now=now + dt) == []
        assert not router._replica("r1").alive
        # operator reset: release() lifts the quarantine and strikes
        ctl.release("r1")
        acts = ctl.step(now=now + 200.0)
        assert [a.action for a in acts] == ["restart"]
        assert router._replica("r1").alive
        # actions counted by (action, reason)
        snap = get_registry().collect()
        series = snap["paddle_controller_actions_total"]["series"]
        assert series.get("restart,replica_dead", 0) >= 3
        assert series.get("quarantine,breaker_tripped", 0) >= 1


# ---------------------------------------------------------------------------
# autoscale: warm pool up/down with hysteresis
# ---------------------------------------------------------------------------

def test_controller_autoscale_up_down(model):
    spare = ContinuousServingEngine(model, **ENGINE_KW)
    router = _router(model)
    p = np.random.RandomState(1).randint(0, 128, (1, 20)).astype(np.int64)
    with router:
        want = np.asarray(router.generate(p, max_new_tokens=3,
                                          timeout=600).numpy())
        ctl = FleetController(router, history=_private_history(),
                              warm_pool=[spare], min_replicas=2,
                              cooldown_s=1.0, up_load_tokens=100.0,
                              down_idle_s=2.0)
        # overload: mean live load over threshold -> join the spare
        router.replicas[0].inflight = {1: 200}
        router.replicas[1].inflight = {2: 200}
        acts = ctl.step(now=10.0)
        assert [a.action for a in acts] == ["scale_up"]
        assert acts[0].reason == "overload" and acts[0].value >= 100.0
        assert len(router.replicas) == 3 and ctl.warm_pool == []
        new_rid = acts[0].target
        assert router._replica(new_rid).alive
        # the new replica serves bit-identically
        router.replicas[0].inflight = {}
        router.replicas[1].inflight = {}
        got = np.asarray(router.generate(p, max_new_tokens=3,
                                         timeout=600).numpy())
        np.testing.assert_array_equal(got, want)
        # still overloaded inside the cooldown: no second scale-up even
        # with a pool (hysteresis)
        ctl.warm_pool.append(ContinuousServingEngine(model, **ENGINE_KW))
        router.replicas[0].inflight = {1: 500}
        assert ctl.step(now=10.5) == []
        router.replicas[0].inflight = {}
        ctl.warm_pool.pop()
        # idle must be SUSTAINED for down_idle_s before draining
        assert ctl.step(now=20.0) == []          # idle clock starts
        assert ctl.step(now=21.0) == []          # not sustained yet
        acts = ctl.step(now=22.5)
        assert [a.action for a in acts] == ["scale_down"]
        assert acts[0].reason == "idle"
        assert len(router.replicas) == 2 and len(ctl.warm_pool) == 1
        # min_replicas floor: never drains below it
        for t in (30.0, 40.0, 50.0):
            assert ctl.step(now=t) == []
        assert len(router.replicas) == 2
        # the fleet still serves after the full cycle
        got = np.asarray(router.generate(p, max_new_tokens=3,
                                         timeout=600).numpy())
        np.testing.assert_array_equal(got, want)


def test_controller_no_flap_on_steady_workload(model):
    """Flap test: a steady workload (constant moderate load, no burn,
    healthy replicas) must produce ZERO actions over many reconcile
    passes — hysteresis + cooldowns make oscillation impossible."""
    spare = ContinuousServingEngine(model, **ENGINE_KW)
    router = _router(model)
    with router:
        ctl = FleetController(router, history=_private_history(),
                              warm_pool=[spare], min_replicas=1,
                              cooldown_s=1.0, up_load_tokens=200.0,
                              down_idle_s=5.0)
        # moderate steady load: above zero (never idle), below the
        # scale-up threshold, no SLO burn
        router.replicas[0].inflight = {1: 50}
        router.replicas[1].inflight = {2: 50}
        for i in range(40):
            assert ctl.step(now=100.0 + 0.5 * i) == []
        assert ctl.actions == []
        assert len(router.replicas) == 2 and len(ctl.warm_pool) == 1
        router.replicas[0].inflight = {}
        router.replicas[1].inflight = {}


# ---------------------------------------------------------------------------
# role flipping (disagg)
# ---------------------------------------------------------------------------

def test_controller_role_flip_rebalances_disagg(model):
    router = _router(model, n=3, disagg=True, prefill_replicas=2)
    p = np.random.RandomState(2).randint(0, 128, (1, 24)).astype(np.int64)
    with router:
        want = np.asarray(router.generate(p, max_new_tokens=3,
                                          timeout=600).numpy())
        ctl = FleetController(router, history=_private_history(),
                              cooldown_s=1.0, flip_ratio=3.0)
        assert [r.role for r in router.replicas] == ["prefill", "prefill",
                                                     "decode"]
        # decode side drowning, prefill idle: flip one prefill replica
        router.replicas[2].inflight = {1: 300}
        acts = ctl.step(now=10.0)
        assert [a.action for a in acts] == ["role_flip"]
        assert acts[0].reason == "queue_imbalance"
        roles = sorted(r.role for r in router.replicas)
        assert roles == ["decode", "decode", "prefill"]
        flipped = router._replica(acts[0].target)
        assert flipped.role == "decode" and flipped.alive
        # each side keeps >= 1 replica: the last prefill never flips,
        # however lopsided the pressure (and cooldown holds regardless)
        for t in (11.5, 13.0, 14.5):
            assert ctl.step(now=t) == []
        assert sorted(r.role for r in router.replicas) == [
            "decode", "decode", "prefill"]
        router.replicas[2].inflight = {}
        # disagg pipeline still bit-identical after the flip
        got = np.asarray(router.generate(p, max_new_tokens=3,
                                         timeout=600).numpy())
        np.testing.assert_array_equal(got, want)


# ---------------------------------------------------------------------------
# graceful degradation: shed heaviest tenant + decode cap, restore
# ---------------------------------------------------------------------------

def _burn_rig():
    """Private history + alert engine over controllable SLO counters."""
    reg = MetricRegistry()
    bad = reg.counter("paddle_slo_violations_total", labels=("slo",))
    good = reg.counter("paddle_slo_goodput_total", labels=("slo",))
    hist = MetricsHistory(capacity=256, registry=reg)
    engine = alerts.AlertEngine(history=hist)
    engine.add_rule(alerts.BurnRateRule(
        name="slo_burn", budget=0.1, fast_window_s=2.0, slow_window_s=4.0,
        factor=1.0, severity="page"))
    engine.attach(hist)
    return hist, engine, good, bad


def test_controller_shed_escalation_and_restore(model):
    hist, engine, good, bad = _burn_rig()
    router = _router(model, tenant_quotas={"hog": (1000, 0.0),
                                           "mid": (1000, 0.0)})
    with router:
        # usage ranking: hog ate the most, mid some
        router.quota.admit("hog", 400)
        router.quota.admit("mid", 100)
        ctl = FleetController(router, history=hist, alert_engine=engine,
                              cooldown_s=1.0, degraded_max_new=4,
                              shed_scale=0.25, min_replicas=2)
        for t in range(5):
            bad.inc(slo="request")
            hist.tick(now=float(t))
        assert "slo_burn" in engine.active
        acts = ctl.step(now=5.0)
        assert [a.action for a in acts] == ["shed"]
        assert acts[0].reason == "slo_burn"
        assert acts[0].target == "hog"          # heaviest consumer first
        assert router.quota.shed_scales() == {"hog": 0.25}
        assert router.max_new_cap == 4
        snap = get_registry().collect()
        assert snap["paddle_controller_degraded"]["series"][""] == 1
        # the tightened bucket bites: hog is over 1000*0.25 already
        with pytest.raises(Rejected) as exc:
            router.quota.admit("hog", 10)
        assert exc.value.reason == "tenant_quota"
        # compliant tenant unaffected
        assert router.quota.admit("mid", 10) is not None
        # the router now caps per-request decode budgets
        p = np.random.RandomState(3).randint(0, 128, (1, 16)) \
            .astype(np.int64)
        out = np.asarray(router.generate(p, max_new_tokens=32,
                                         timeout=600).numpy())
        assert out.shape[1] == 16 + 4           # capped at 4 new tokens
        # STILL burning after the cooldown: escalate to the next tenant
        bad.inc(slo="request")
        hist.tick(now=6.0)
        acts = ctl.step(now=6.5)
        assert [a.action for a in acts] == ["shed"]
        assert acts[0].target == "mid"
        assert set(router.quota.shed_scales()) == {"hog", "mid"}
        # burn clears -> restore (after a full clear cooldown)
        for t in range(7, 16):
            good.inc(slo="request")
            hist.tick(now=float(t))
        assert "slo_burn" not in engine.active
        assert ctl.step(now=15.2) == []         # clear, but not for long
        acts = ctl.step(now=16.5)
        assert [a.action for a in acts] == ["restore"]
        assert acts[0].reason == "recovered"
        assert router.quota.shed_scales() == {}
        assert router.max_new_cap is None
        snap = get_registry().collect()
        assert snap["paddle_controller_degraded"]["series"][""] == 0
        # un-shed: hog admits again (budget 1000, used 410)
        assert router.quota.admit("hog", 10) is not None
    engine.detach()


def test_quota_full_shed_rejects_unlimited_tenant(model):
    """shed(tenant, 0) rejects outright — even a tenant with no
    configured budget can be shut off under degradation."""
    from paddle_tpu.inference.fleet.quota import TenantQuotaManager
    q = TenantQuotaManager(MemKVStore())
    assert q.admit("free", 100) is None          # unlimited
    q.shed("free", 0.0)
    with pytest.raises(Rejected):
        q.admit("free", 1)
    q.restore("free")
    assert q.admit("free", 1) is None
    assert q.tenants_by_usage() == ["free"]


# ---------------------------------------------------------------------------
# knobs, state provider, telemetry
# ---------------------------------------------------------------------------

def test_controller_env_knobs(model, monkeypatch):
    monkeypatch.setenv("PADDLE_CONTROLLER_INTERVAL_S", "0.2")
    monkeypatch.setenv("PADDLE_CONTROLLER_COOLDOWN_S", "7.5")
    monkeypatch.setenv("PADDLE_CONTROLLER_UP_LOAD_TOKENS", "123")
    monkeypatch.setenv("PADDLE_CONTROLLER_DOWN_IDLE_S", "3.5")
    monkeypatch.setenv("PADDLE_CONTROLLER_FLIP_RATIO", "2.5")
    monkeypatch.setenv("PADDLE_CONTROLLER_BREAKER_N", "4")
    monkeypatch.setenv("PADDLE_CONTROLLER_BREAKER_WINDOW_S", "30")
    monkeypatch.setenv("PADDLE_CONTROLLER_RESTART_BACKOFF_S", "0.25")
    monkeypatch.setenv("PADDLE_CONTROLLER_DEGRADED_MAX_NEW", "8")
    monkeypatch.setenv("PADDLE_CONTROLLER_SHED_SCALE", "0.1")
    router = _router(model)
    ctl = FleetController(router, history=_private_history())
    assert ctl.interval_s == 0.2
    assert ctl.cooldown_s == 7.5
    assert ctl.up_load_tokens == 123.0
    assert ctl.down_idle_s == 3.5
    assert ctl.flip_ratio == 2.5
    assert ctl.breaker_n == 4
    assert ctl.breaker_window_s == 30.0
    assert ctl.restart_backoff_s == 0.25
    assert ctl.degraded_max_new == 8
    assert ctl.shed_scale == 0.1
    # constructor kwargs win over env
    ctl2 = FleetController(router, history=_private_history(),
                           cooldown_s=1.0, breaker_n=2)
    assert ctl2.cooldown_s == 1.0 and ctl2.breaker_n == 2
    assert set(CONTROLLER_ACTIONS) == {"scale_up", "scale_down",
                                       "role_flip", "restart",
                                       "quarantine", "shed", "restore"}


def test_controller_state_provider_and_ledger(model):
    from paddle_tpu.profiler import flight_recorder as flight
    router = _router(model)
    with router:
        ctl = FleetController(router, history=_private_history(),
                              cooldown_s=0.0, restart_backoff_s=0.01,
                              min_replicas=2, down_idle_s=1e6)
        with ctl:
            assert "fleet_controller" in flight._STATE_PROVIDERS
            router.kill_replica("r1")
            _wait_engine_down(router, "r1")
            ctl.step(now=50.0)
            deadline = time.monotonic() + 5
            while (not router._replica("r1").alive
                   and time.monotonic() < deadline):
                ctl.step(now=60.0)
                time.sleep(0.02)
            state = flight._STATE_PROVIDERS["fleet_controller"]()
            assert state["running"] is True
            acts = state["recent_actions"]
            assert acts and acts[-1]["action"] == "restart"
            assert acts[-1]["reason"] == "replica_dead"
            assert acts[-1]["target"] == "r1"
            assert "cooldowns" in state and "restart" in state["cooldowns"]
            assert state["quarantined"] == []
            assert state["degraded"] is False
        assert "fleet_controller" not in flight._STATE_PROVIDERS


# ---------------------------------------------------------------------------
# satellites: requeue budget, empty-fleet fast fail, stall directive
# ---------------------------------------------------------------------------

def test_fleet_requeue_budget_exhausted(model, monkeypatch):
    """Every replica dies under the request: after
    PADDLE_FLEET_MAX_ATTEMPTS attempts it fails with a structured
    Rejected(reason="attempts_exhausted") and a traced terminal span —
    not a retry loop into the client timeout."""
    monkeypatch.setenv("PADDLE_FLEET_MAX_ATTEMPTS", "2")
    fault.install("kill:replica=r0,request=1;kill:replica=r1,request=1;"
                  "kill:replica=r2,request=1")
    router = _router(model, n=3)
    assert router.max_attempts == 2
    p = np.random.RandomState(4).randint(0, 128, (1, 16)).astype(np.int64)
    reg = get_registry()
    fam = reg.collect().get("paddle_fleet_rejected_total", {})
    before = dict(fam.get("series", {}))
    with router:
        t0 = time.monotonic()
        with pytest.raises(Rejected) as exc:
            router.generate(p, max_new_tokens=2, timeout=600)
        assert exc.value.reason == "attempts_exhausted"
        assert time.monotonic() - t0 < 60, "burned the client timeout"
    fam = reg.collect()["paddle_fleet_rejected_total"]
    delta = {k: v - before.get(k, 0) for k, v in fam["series"].items()}
    assert delta.get("default,attempts_exhausted", 0) == 1
    assert "attempts_exhausted" in REJECTION_REASONS


def test_fleet_requeue_budget_traced_terminal(model, monkeypatch):
    monkeypatch.setenv("PADDLE_FLEET_MAX_ATTEMPTS", "1")
    fault.install("kill:replica=r0,request=1;kill:replica=r1,request=1")
    router = _router(model)
    p = np.random.RandomState(5).randint(0, 128, (1, 16)).astype(np.int64)
    with router:
        with pytest.raises(Rejected):
            router.generate(p, max_new_tokens=2, timeout=600)
    # the trace is terminal with the structured reason on its done span
    recent = rt.recent_timelines(4)
    mine = [tl for tl in recent if tl["status"] == "rejected" and any(
        s["name"] == "done"
        and (s.get("tags") or {}).get("reason") == "attempts_exhausted"
        for s in tl["spans"])]
    assert mine, [(tl["status"], tl["spans"][-1]) for tl in recent]


def test_fleet_fast_fail_on_empty_fleet(model):
    """Every replica dead or draining => queued and new requests get
    Rejected("no_replicas") immediately, not after the client timeout;
    the rejection is counted and traced."""
    router = _router(model)
    p = np.random.RandomState(6).randint(0, 128, (1, 16)).astype(np.int64)
    reg = get_registry()
    fam = reg.collect().get("paddle_fleet_rejected_total", {})
    before = dict(fam.get("series", {}))
    with router:
        router.kill_replica("r0")
        router.kill_replica("r1")
        t0 = time.monotonic()
        with pytest.raises(Rejected) as exc:
            router.generate(p, max_new_tokens=2, tenant="acme",
                            timeout=600)
        dt = time.monotonic() - t0
        assert exc.value.reason == "no_replicas"
        assert dt < 5.0, f"empty-fleet rejection took {dt:.1f}s"
    fam = reg.collect()["paddle_fleet_rejected_total"]
    delta = {k: v - before.get(k, 0) for k, v in fam["series"].items()}
    assert delta.get("acme,no_replicas", 0) == 1
    tl = rt.recent_timelines(2)
    assert any(t["status"] == "rejected" and any(
        s["name"] == "done"
        and (s.get("tags") or {}).get("reason") == "no_replicas"
        for s in t["spans"]) for t in tl)


def test_fleet_stall_directive_slows_but_serves(model):
    """stall:replica=R,seconds=T: the replica's serve loop sleeps at a
    tick boundary — output parity is untouched, the firing is counted,
    and the replica is never marked dead (straggler, not corpse)."""
    p = np.random.RandomState(7).randint(0, 128, (1, 16)).astype(np.int64)
    router = _router(model, n=1)
    c = fault.elastic_telemetry()["events"]
    s0 = c.value(kind="stall")
    with router:
        want = np.asarray(router.generate(p, max_new_tokens=2,
                                          timeout=600).numpy())
        fault.install("stall:replica=r0,seconds=0.3")
        t0 = time.monotonic()
        got = np.asarray(router.generate(p + 1, max_new_tokens=2,
                                         timeout=600).numpy())
        assert time.monotonic() - t0 >= 0.3
        assert router._replica("r0").alive
    assert c.value(kind="stall") == s0 + 1
    oracle = np.asarray(model.generate(
        paddle.to_tensor(p + 1), max_new_tokens=2)._data)
    np.testing.assert_array_equal(got, oracle)


# ---------------------------------------------------------------------------
# ACCEPTANCE: seeded 10x burst + mid-run replica kill, on vs off
# ---------------------------------------------------------------------------

def _chaos_replay(model, trace, controller_on, monkeypatch):
    """One seeded replay with r1 killed at its 4th routed request.
    Controller-on heals through BOTH actuator families: supervision
    restarts the dead replica, and sustained burn sheds tenants
    (scale 0 = reject outright) until the burn clears. Returns
    (report dict, harness, controller_or_None)."""
    import paddle_tpu.profiler as profiler
    from paddle_tpu.profiler import timeseries as ts

    router = ServingRouter(
        model, num_replicas=2, store=MemKVStore(), heartbeat_ttl=600.0,
        tenant_quotas={"hog": (0, 0.0), "pay": (0, 0.0)},
        engine_kwargs=dict(max_batch_size=4, max_len=64, page_size=16,
                           prefill_chunk_tokens=32))
    ts.reset()                      # fresh GLOBAL history for this run
    hist = profiler.history()
    engine = alerts.AlertEngine(history=hist)
    # eager burn rule: the controller's feed must CONFIRM while burst
    # arrivals are still coming, so shedding has admissions left to
    # refuse (the harness's own recovery metric keeps the standard
    # budget below — sensing and acting thresholds are independent)
    engine.add_rule(alerts.BurnRateRule(
        name="slo_burn", budget=0.1, fast_window_s=1.0,
        slow_window_s=2.0, factor=1.0, severity="page"))
    engine.attach(hist)
    ctl = None
    try:
        with router:
            warm = np.arange(8, dtype=np.int64)[None]
            router.generate(warm, max_new_tokens=1, timeout=600)
            t0 = time.perf_counter()
            router.generate(warm + 8, max_new_tokens=1, timeout=600)
            warm_s = time.perf_counter() - t0
            monkeypatch.setenv("PADDLE_SLO_TTFT_MS",
                               str(round(max(2.0 * warm_s, 0.1) * 1e3, 1)))
            rt.reset_slo_monitor()
            fault.install("kill:replica=r1,request=4")
            if controller_on:
                # shed NOW, restart on a long backoff: restoring a
                # replica into an already-drowning host only adds
                # contention — the fleet heals once the storm passes
                ctl = FleetController(
                    router, history=hist, alert_engine=engine,
                    cooldown_s=0.5, restart_backoff_s=6.0,
                    interval_s=0.1, shed_scale=0.0, min_replicas=2)
                ctl.start()
            harness = replay.ReplayHarness(
                router, trace, vocab_size=128, history=hist,
                alert_engine=engine, tick_interval_s=0.25,
                recover_window_s=1.5, budget=0.2, factor=1.0,
                cooldown_s=6.0, collect_outputs=True, time_scale=1.5)
            report = harness.run().as_dict()
            if ctl is not None:
                ctl.stop()
            report["alive_at_end"] = sum(
                r.alive for r in router.replicas)
    finally:
        if ctl is not None:
            ctl.stop()
        fault.clear()
        engine.detach()
        rt.reset_slo_monitor()
    return report, harness, ctl


def test_controller_chaos_acceptance(model, monkeypatch):
    """Seeded 10x bursty replay, r1 killed mid-run. Controller-on: the
    burn alert fires and clears, the dead replica is restarted AND
    over-quota load is shed, every admitted stream delivers exactly
    once and bit-identical to an undisturbed oracle (shed requests
    fail with a structured rejection, never a dropped/garbled stream),
    and time-to-recover is finite and lower than the controller-off
    run on the same seed."""
    trace = replay.make_trace(
        preset="bursty", seed=13, duration_s=7.0, rate_rps=0.7,
        burst_factor=10.0, burst_start_frac=0.25, burst_dur_frac=0.35,
        tenants=("hog", "pay"), prompt_len=(4, 12), new_tokens=(1, 2))
    # undisturbed per-request oracle (the exact prompts the harness
    # will fire, straight through the bare model)
    oracle = []
    for req in trace.requests:
        prompt = np.random.default_rng(req.seed).integers(
            0, 128, req.prompt_len).astype(np.int64)[None]
        oracle.append(np.asarray(model.generate(
            paddle.to_tensor(prompt),
            max_new_tokens=req.new_tokens)._data))

    # controller-off FIRST: it doubles as the warm-up for the ragged
    # program family, so the measured pair differs only in the
    # controller (a cold-compile storm in one run would skew the
    # recovery comparison)
    rep_off, h_off, _ = _chaos_replay(model, trace, False, monkeypatch)
    rep_on, h_on, ctl = _chaos_replay(model, trace, True, monkeypatch)

    # zero dropped or duplicated streams: every request reaches exactly
    # one terminal outcome — delivered ok, or a structured shed
    # rejection; never an error, timeout, or silent drop
    st_on = rep_on["statuses"]
    assert set(st_on) <= {"ok", "rejected"}, st_on
    assert st_on.get("ok", 0) + st_on.get("rejected", 0) == len(trace)
    assert st_on.get("ok", 0) >= 1
    for r in (x for x in h_on.results if x["status"] == "rejected"):
        assert r["reason"] == "tenant_quota", r
    # every delivered output bit-identical to the undisturbed oracle
    # (kill, requeue and degradation never change tokens), and every
    # ok result produced exactly one output
    n_out = 0
    for i, res in enumerate(h_on.results):
        if res["status"] == "ok":
            assert h_on.outputs[i] is not None
            np.testing.assert_array_equal(h_on.outputs[i], oracle[i])
            n_out += 1
        else:
            assert h_on.outputs[i] is None
    assert n_out == st_on.get("ok", 0)
    # the fault actually fired and the controller healed it: the burn
    # alert fired then cleared, the replica was restarted, load was
    # shed, fleet whole again
    fired = [t for t in rep_on["alerts"]["transitions"]
             if t["action"] == "fired" and t["rule"] == "slo_burn"]
    cleared = [t for t in rep_on["alerts"]["transitions"]
               if t["action"] == "cleared" and t["rule"] == "slo_burn"]
    assert fired, "burst+kill never fired the burn alert"
    assert cleared and cleared[-1]["t"] >= fired[-1]["t"]
    assert rep_on["alerts"]["active"] == []
    kinds = {a.action for a in ctl.actions}
    assert any(a.action == "restart" and a.target == "r1"
               for a in ctl.actions), [repr(a) for a in ctl.actions]
    assert "shed" in kinds, [repr(a) for a in ctl.actions]
    assert rep_on["alive_at_end"] == 2
    # bounded p99 over delivered requests
    assert rep_on.get("p99_latency_s") is not None
    ttr_on = rep_on["time_to_recover_s"]
    assert ttr_on is not None and ttr_on >= 0.0, "controller-on never " \
        "recovered"

    # controller-off on the SAME seed: the replica stays dead, nothing
    # sheds (everything is served, slowly), recovery is strictly
    # slower — or never observed inside the same window
    assert rep_off["statuses"].get("ok", 0) == len(trace), \
        "requeue-to-survivor must still deliver everything"
    for i, want in enumerate(oracle):
        np.testing.assert_array_equal(h_off.outputs[i], want)
    assert rep_off["alive_at_end"] == 1          # nobody healed it
    ttr_off = rep_off["time_to_recover_s"]
    # controller-on recovery is restart-gated: it cannot beat its own
    # restart_backoff_s (6.0) + recover_window_s (1.5) no matter how
    # fast the host is, while the off-run's survivor can drain the tiny
    # 4-12-token backlog in a couple of seconds on an unloaded box. So
    # require on-run recovery to beat the off-run OR to land within its
    # structural floor — still an absolute bound on healing time, minus
    # the host-speed coin flip.
    floor_s = 6.0 + 1.5
    assert ttr_off is None or ttr_on < max(ttr_off, floor_s), \
        (ttr_on, ttr_off)
