"""MoE / expert-parallel tests (reference behavior:
``paddle.incubate.distributed.models.moe.MoELayer`` + gates)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.incubate.distributed.models.moe import (
    MoELayer, NaiveGate, SwitchGate, GShardGate, ExpertFFN,
)


def _x(b=2, s=8, d=16, seed=0):
    return paddle.to_tensor(
        np.random.default_rng(seed).normal(size=(b, s, d)).astype(np.float32))


def test_moe_fused_matches_dense_mixture():
    """With capacity >= tokens (no drops), MoE == explicit top-k mixture."""
    paddle.seed(0)
    d, dh, e, k = 16, 32, 4, 2
    moe = MoELayer(d_model=d, num_experts=e, d_hidden=dh, gate="gshard",
                   top_k=k, capacity_factor=float(e))   # capacity = tokens*k
    x = _x(d=d)
    out = moe(x)
    assert out.shape == x.shape
    assert moe.aux_loss is not None and float(moe.aux_loss) > 0

    # manual dense mixture using the same weights
    xa = jnp.asarray(x.numpy()).reshape(-1, d)
    gw = jnp.asarray(moe.gate.weight.numpy())
    probs = jax.nn.softmax(xa @ gw, axis=-1)
    topv, topi = jax.lax.top_k(probs, k)
    topv = topv / topv.sum(-1, keepdims=True)
    f = moe.fused
    w1, b1 = jnp.asarray(f.w1.numpy()), jnp.asarray(f.b1.numpy())
    w2, b2 = jnp.asarray(f.w2.numpy()), jnp.asarray(f.b2.numpy())
    h = jnp.einsum("sd,edh->esh", xa, w1) + b1[:, 0][:, None]
    h = jax.nn.gelu(h)
    eo = jnp.einsum("esh,ehd->esd", h, w2) + b2[:, 0][:, None]   # [E, S, d]
    ref = jnp.zeros_like(xa)
    for j in range(k):
        ref = ref + topv[:, j:j + 1] * jnp.take_along_axis(
            eo, topi[:, j][None, :, None], axis=0)[0]
    np.testing.assert_allclose(out.numpy().reshape(-1, d), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_moe_capacity_drops_tokens():
    paddle.seed(1)
    d, e = 8, 2
    moe = MoELayer(d_model=d, num_experts=e, d_hidden=16, gate="switch",
                   top_k=1, capacity_factor=0.25)   # tiny capacity
    x = _x(b=1, s=16, d=d, seed=2)
    out = moe(x)
    # some rows must be fully dropped (zero output)
    norms = np.linalg.norm(out.numpy().reshape(-1, d), axis=-1)
    assert (norms < 1e-6).any()
    assert (norms > 1e-6).any()


def test_moe_gates():
    paddle.seed(2)
    for gate_cls, k in [(NaiveGate, 2), (SwitchGate, 1), (GShardGate, 2)]:
        gate = gate_cls(16, num_expert=4, world_size=1, top_k=k)
        assert gate.num_experts == 4
        moe = MoELayer(d_model=16, num_experts=4, d_hidden=8, gate=gate,
                       top_k=gate.top_k)
        out = moe(_x(seed=3))
        assert out.shape == [2, 8, 16]
        if isinstance(gate, GShardGate):
            assert float(moe.aux_loss) > 0.0
        if gate_cls is NaiveGate:
            assert float(moe.aux_loss) == 0.0


def test_moe_expert_list_path():
    """Reference-style experts=list-of-Layers path."""
    paddle.seed(3)
    d = 8
    experts = [paddle.nn.Linear(d, d) for _ in range(2)]
    moe = MoELayer(d_model=d, experts=experts, gate="naive", top_k=1,
                   capacity_factor=4.0)
    x = _x(b=1, s=4, d=d, seed=4)
    out = moe(x)
    assert out.shape == x.shape


def test_moe_backward_trains():
    paddle.seed(4)
    moe = MoELayer(d_model=16, num_experts=4, d_hidden=32, gate="gshard",
                   top_k=2, capacity_factor=2.0)
    opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=moe.parameters())
    x = _x(seed=5)
    target = paddle.to_tensor(np.zeros((2, 8, 16), np.float32))
    losses = []
    for _ in range(5):
        out = moe(x)
        loss = ((out - target) ** 2).mean() + 0.01 * moe.aux_loss
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    # gate + expert weights both received grads (were updated)
    assert moe.gate.weight.grad is None  # cleared
    assert np.isfinite(losses).all()


def test_moe_expert_parallel_mesh():
    """Fused MoE under jit on a dp mesh: expert dim sharded over dp (the
    reference's default ep group); parity vs single-device output."""
    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.framework.functional import FunctionalModule
    from jax.sharding import NamedSharding, PartitionSpec as P

    paddle.seed(5)
    d, e = 16, 4
    moe = MoELayer(d_model=d, num_experts=e, d_hidden=32, gate="gshard",
                   top_k=2, capacity_factor=float(e))
    x = _x(b=4, s=8, d=d, seed=6)
    ref = moe(x).numpy()

    mesh = mesh_mod.init_mesh({"dp": 4, "mp": 2})
    try:
        fm = FunctionalModule(moe, training=False)
        p_arrs = fm.param_arrays()
        # shard the stacked expert weights over dp (expert parallelism)
        specs = []
        for p in fm.params:
            if p.ndim == 3 and p.shape[0] == e:
                specs.append(P("dp", None, None))
            else:
                specs.append(P())
        p_arrs = [jax.device_put(a, NamedSharding(mesh, s))
                  for a, s in zip(p_arrs, specs)]
        xa = jax.device_put(jnp.asarray(x.numpy()),
                            NamedSharding(mesh, P("dp", None, None)))

        def fwd(ps, xa):
            out, _ = fm(ps, [], fm.next_key(), xa)
            return out

        with mesh:
            out = jax.jit(fwd)(p_arrs, xa)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)
    finally:
        mesh_mod.reset_mesh()
