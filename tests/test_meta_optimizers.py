"""DGC + LocalSGD dp-axis meta-optimizers (reference:
``fleet/meta_optimizers/dgc_optimizer.py`` / ``localsgd_optimizer.py``;
VERDICT round-4 item 8). DGC's convergence-relevant math — momentum
correction, residual accumulation, top-k selection, dense rampup — is
checked against a NumPy oracle; the wire format is XLA's (dense masked
allreduce), by design."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.fleet.meta_optimizers import (
    DGCMomentumOptimizer, LocalSGDOptimizer)


def _param(shape, seed):
    rng = np.random.default_rng(seed)
    p = paddle.to_tensor(rng.normal(size=shape).astype("float32"))
    p.stop_gradient = False
    return p


def _set_grad(p, g):
    t = paddle.to_tensor(np.asarray(g, dtype="float32"))
    p.grad = t


def test_dgc_matches_numpy_oracle():
    rng = np.random.default_rng(0)
    w0 = rng.normal(size=(20,)).astype(np.float32)
    p = paddle.to_tensor(w0.copy())
    p.stop_gradient = False
    lr, mom, sparsity = 0.1, 0.9, 0.8      # keep top 20% of 20 -> 4
    opt = DGCMomentumOptimizer(learning_rate=lr, momentum=mom,
                               parameters=[p], rampup_begin_step=0,
                               sparsity=[sparsity])

    w = w0.copy()
    u = np.zeros_like(w)
    v = np.zeros_like(w)
    for step in range(5):
        g = rng.normal(size=w.shape).astype(np.float32)
        _set_grad(p, g)
        opt.step()
        # oracle: momentum correction -> residual -> top-k -> PLAIN SGD on
        # the synced sparse update (momentum lives only in the local
        # correction u once compression engages — the reference
        # dgc_momentum op's momentum-then-SGD switch; ADVICE round-5 #1)
        u = mom * u + g
        v = v + u
        keep_n = max(1, int(round((1 - sparsity) * w.size)))
        thresh = np.sort(np.abs(v))[w.size - keep_n]
        mask = np.abs(v) >= thresh
        update = np.where(mask, v, 0.0)
        v = np.where(mask, 0.0, v)
        u = np.where(mask, 0.0, u)
        w = w - lr * update
        np.testing.assert_allclose(p.numpy(), w, rtol=1e-5, atol=1e-6,
                                   err_msg=f"step {step}")


def test_dgc_rampup_is_dense():
    """Before rampup_begin_step the exchange is DENSE (no compression,
    no residual state) — reference rampup contract."""
    p = _param((10,), 1)
    opt = DGCMomentumOptimizer(learning_rate=1.0, momentum=0.0,
                               parameters=[p], rampup_begin_step=2,
                               sparsity=[0.9])
    w_before = p.numpy().copy()
    g = np.full(10, 0.5, np.float32)
    _set_grad(p, g)
    opt.step()
    np.testing.assert_allclose(p.numpy(), w_before - 0.5, rtol=1e-6)
    assert not opt._v, "dense warmup must not accumulate residuals"


def test_dgc_residual_eventually_transmits():
    """A small-but-persistent gradient coordinate must eventually exceed
    the top-k threshold through residual accumulation — THE property
    that makes DGC converge."""
    p = _param((8,), 2)
    opt = DGCMomentumOptimizer(learning_rate=1.0, momentum=0.0,
                               parameters=[p], sparsity=[0.875])  # top-1
    w0 = p.numpy().copy()
    # coordinate 0 large once; coordinate 7 small every step
    for step in range(6):
        g = np.zeros(8, np.float32)
        g[0] = 1.0 if step == 0 else 0.0
        g[7] = 0.3
        _set_grad(p, g)
        opt.step()
    # after 6 steps the accumulated 0.3*k at coord 7 must have been
    # selected at least once (1.8 total minus residual in flight)
    moved = w0[7] - p.numpy()[7]
    assert moved > 0.5, moved


def test_dgc_dense_warmup_keeps_momentum():
    """Dense rampup steps still run classic momentum SGD (vel EMA);
    only the compressed regime switches to plain SGD."""
    p = _param((6,), 9)
    lr, mom = 0.1, 0.5
    opt = DGCMomentumOptimizer(learning_rate=lr, momentum=mom,
                               parameters=[p], rampup_begin_step=10,
                               sparsity=[0.5])
    w = p.numpy().copy()
    vel = np.zeros_like(w)
    g = np.full(6, 0.4, np.float32)
    for _ in range(3):
        _set_grad(p, g)
        opt.step()
        vel = mom * vel + g
        w = w - lr * vel
    np.testing.assert_allclose(p.numpy(), w, rtol=1e-5, atol=1e-6)


def test_dgc_applies_grad_clip():
    """grad_clip (a ClipGradBy*) must be applied to the raw grads before
    the DGC math — previously it was silently ignored (ADVICE #4)."""
    from paddle_tpu import nn
    p = _param((4,), 6)
    clip = nn.ClipGradByGlobalNorm(clip_norm=1.0)
    opt = DGCMomentumOptimizer(learning_rate=1.0, momentum=0.0,
                               parameters=[p], rampup_begin_step=10,
                               grad_clip=clip)
    w0 = p.numpy().copy()
    g = np.full(4, 10.0, np.float32)       # global norm 20 -> scaled by 1/20
    _set_grad(p, g)
    opt.step()
    expected = w0 - g / np.linalg.norm(g)  # clipped to unit global norm
    np.testing.assert_allclose(p.numpy(), expected, rtol=1e-5, atol=1e-6)


def test_localsgd_counts_and_averages(monkeypatch):
    p = _param((4,), 3)
    inner = paddle.optimizer.SGD(learning_rate=0.1, parameters=[p])
    ls = LocalSGDOptimizer(inner, k_steps=3)

    calls = []
    monkeypatch.setattr(
        "paddle_tpu.distributed.fleet.meta_optimizers._world_size",
        lambda: 2)
    from paddle_tpu.distributed import collective as coll
    monkeypatch.setattr(coll, "all_reduce",
                        lambda t, *a, **k: calls.append(1) or
                        setattr(t, "_data", t._data * 2))  # sum of 2 equals
    for step in range(7):
        _set_grad(p, np.ones(4, np.float32))
        ls.step()
        inner.clear_grad()
    # averaging at steps 3 and 6 only (1 param x 2 events)
    assert len(calls) == 2, calls


def test_fleet_strategy_wires_dgc_and_localsgd():
    from paddle_tpu.distributed import fleet, mesh as mesh_mod
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 8}
    strategy.dgc = True
    strategy.dgc_configs = {"rampup_begin_step": 0, "sparsity": [0.95]}
    fleet.init(is_collective=True, strategy=strategy)
    try:
        p = _param((6,), 4)
        mopt = paddle.optimizer.Momentum(learning_rate=0.01, momentum=0.9,
                                         parameters=[p])
        dopt = fleet.distributed_optimizer(mopt, strategy)
        assert isinstance(dopt._inner_opt, DGCMomentumOptimizer)
        _set_grad(p, np.ones(6, np.float32))
        before = p.numpy().copy()
        dopt.step()
        assert not np.allclose(p.numpy(), before)

        strategy2 = fleet.DistributedStrategy()
        strategy2.localsgd = True
        strategy2.localsgd_configs = {"k_steps": 4}
        p2 = _param((6,), 5)
        sopt = paddle.optimizer.SGD(learning_rate=0.01, parameters=[p2])
        dopt2 = fleet.distributed_optimizer(sopt, strategy2)
        assert isinstance(dopt2._inner_opt, LocalSGDOptimizer)
        assert dopt2._inner_opt._k == 4
    finally:
        # neither the installed dp=8 mesh nor the dgc=True module-global
        # strategy may leak into later test files (test_models; any test
        # calling distributed_optimizer without its own fleet.init)
        mesh_mod.reset_mesh()
        fleet._strategy = None
