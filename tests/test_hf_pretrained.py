"""from_pretrained over local HF checkpoints: logits parity vs the
transformers (torch CPU) forward on the same weights — the strongest
possible oracle for the model families (reference: PaddleNLP
from_pretrained + its HF interop)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import LlamaForCausalLM as PTLlama

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


@pytest.fixture(scope="module")
def hf_llama_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("hf_llama")
    cfg = transformers.LlamaConfig(
        vocab_size=96, hidden_size=32, intermediate_size=88,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-5, tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(cfg)
    hf.eval()
    hf.save_pretrained(d)
    return str(d), hf


def test_llama_logits_match_transformers(hf_llama_dir):
    d, hf = hf_llama_dir
    model = PTLlama.from_pretrained(d)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 96, (2, 10)).astype(np.int64)

    with torch.no_grad():
        want = hf(torch.tensor(ids)).logits.float().numpy()
    model.eval()
    got = model(paddle.to_tensor(ids))
    if isinstance(got, tuple):
        got = got[0]
    np.testing.assert_allclose(np.asarray(got.numpy()), want,
                               rtol=2e-4, atol=2e-4)


def test_llama_generate_greedy_matches_transformers(hf_llama_dir):
    d, hf = hf_llama_dir
    model = PTLlama.from_pretrained(d)
    model.eval()
    rng = np.random.RandomState(1)
    ids = rng.randint(0, 96, (1, 6)).astype(np.int64)
    with torch.no_grad():
        want = hf.generate(torch.tensor(ids), max_new_tokens=8,
                           do_sample=False).numpy()
    got = model.generate(paddle.to_tensor(ids), max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(got.numpy()), want)


def test_gpt2_weights_map(tmp_path):
    cfg = transformers.GPT2Config(
        vocab_size=80, n_positions=32, n_embd=24, n_layer=2, n_head=3)
    torch.manual_seed(0)
    hf = transformers.GPT2LMHeadModel(cfg)
    hf.eval()       # GPT-2 defaults to 0.1 dropout — train mode would
    hf.save_pretrained(tmp_path)   # make the oracle nondeterministic

    from paddle_tpu.models import GPTForCausalLM, GPTConfig
    from paddle_tpu.models.pretrained import load_gpt_from_hf
    model = GPTForCausalLM(GPTConfig(
        vocab_size=80, hidden_size=24, num_hidden_layers=2,
        num_attention_heads=3, max_position_embeddings=32))
    load_gpt_from_hf(model, str(tmp_path))

    rng = np.random.RandomState(2)
    ids = rng.randint(0, 80, (2, 8)).astype(np.int64)
    with torch.no_grad():
        want = hf(torch.tensor(ids)).logits.float().numpy()
    model.eval()
    got = model(paddle.to_tensor(ids))
    if isinstance(got, tuple):
        got = got[0]
    np.testing.assert_allclose(np.asarray(got.numpy()), want,
                               rtol=2e-4, atol=2e-4)


def test_bert_logits_match_transformers(tmp_path):
    cfg = transformers.BertConfig(
        vocab_size=120, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=48, type_vocab_size=2)
    torch.manual_seed(0)
    hf = transformers.BertModel(cfg)
    hf.eval()
    hf.save_pretrained(tmp_path)

    from paddle_tpu.models import BertModel
    from paddle_tpu.models.pretrained import (bert_config_from_hf,
                                              load_bert_from_hf)
    model = BertModel(bert_config_from_hf(str(tmp_path)))
    load_bert_from_hf(model, str(tmp_path))
    model.eval()

    rng = np.random.RandomState(3)
    ids = rng.randint(0, 120, (2, 9)).astype(np.int64)
    with torch.no_grad():
        out = hf(torch.tensor(ids))
        want_seq = out.last_hidden_state.numpy()
        want_pooled = out.pooler_output.numpy()
    got = model(paddle.to_tensor(ids))
    got_seq, got_pooled = (got if isinstance(got, tuple) else (got, None))
    np.testing.assert_allclose(np.asarray(got_seq.numpy()), want_seq,
                               rtol=2e-4, atol=2e-4)
    if got_pooled is not None:
        np.testing.assert_allclose(np.asarray(got_pooled.numpy()),
                                   want_pooled, rtol=2e-4, atol=2e-4)


@pytest.fixture(scope="module")
def hf_t5_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("hf_t5")
    cfg = transformers.T5Config(
        vocab_size=96, d_model=32, d_kv=8, d_ff=64, num_layers=2,
        num_heads=4, relative_attention_num_buckets=8,
        relative_attention_max_distance=32, dropout_rate=0.0,
        feed_forward_proj="relu", tie_word_embeddings=True,
        decoder_start_token_id=0, eos_token_id=1, pad_token_id=0)
    torch.manual_seed(0)
    hf = transformers.T5ForConditionalGeneration(cfg)
    hf.eval()
    hf.save_pretrained(d)
    return str(d), hf


def test_t5_logits_match_transformers(hf_t5_dir):
    from paddle_tpu.models import T5ForConditionalGeneration as PT5
    d, hf = hf_t5_dir
    model = PT5.from_pretrained(d)
    model.eval()
    rng = np.random.RandomState(0)
    src = rng.randint(2, 96, (2, 9)).astype(np.int64)
    dec = rng.randint(2, 96, (2, 5)).astype(np.int64)
    with torch.no_grad():
        want = hf(input_ids=torch.tensor(src),
                  decoder_input_ids=torch.tensor(dec)).logits.float().numpy()
    got = model(paddle.to_tensor(src),
                decoder_input_ids=paddle.to_tensor(dec))
    np.testing.assert_allclose(np.asarray(got.numpy()), want,
                               rtol=2e-4, atol=2e-4)


def test_t5_generate_matches_transformers(hf_t5_dir):
    from paddle_tpu.models import T5ForConditionalGeneration as PT5
    d, hf = hf_t5_dir
    model = PT5.from_pretrained(d)
    model.eval()
    rng = np.random.RandomState(1)
    src = rng.randint(2, 96, (1, 7)).astype(np.int64)
    with torch.no_grad():
        want = hf.generate(torch.tensor(src), max_new_tokens=6,
                           do_sample=False).numpy()
    got = np.asarray(model.generate(paddle.to_tensor(src),
                                    max_new_tokens=6).numpy())
    np.testing.assert_array_equal(got[:, :want.shape[1]], want)


def test_t5_v11_untied_gated_matches_transformers(tmp_path):
    """T5 v1.1 style: untied lm_head + gated-gelu FFN."""
    from paddle_tpu.models import T5ForConditionalGeneration as PT5
    cfg = transformers.T5Config(
        vocab_size=80, d_model=24, d_kv=6, d_ff=48, num_layers=2,
        num_heads=4, relative_attention_num_buckets=8,
        relative_attention_max_distance=32, dropout_rate=0.0,
        feed_forward_proj="gated-gelu", tie_word_embeddings=False,
        decoder_start_token_id=0, eos_token_id=1, pad_token_id=0)
    torch.manual_seed(1)
    hf = transformers.T5ForConditionalGeneration(cfg)
    hf.eval()
    d = tmp_path / "t5v11"
    hf.save_pretrained(d)
    model = PT5.from_pretrained(str(d))
    model.eval()
    assert model.lm_head is not None          # untied head materialized
    rng = np.random.RandomState(2)
    src = rng.randint(2, 80, (2, 6)).astype(np.int64)
    dec = rng.randint(2, 80, (2, 4)).astype(np.int64)
    with torch.no_grad():
        want = hf(input_ids=torch.tensor(src),
                  decoder_input_ids=torch.tensor(dec)).logits.float().numpy()
    got = model(paddle.to_tensor(src),
                decoder_input_ids=paddle.to_tensor(dec))
    np.testing.assert_allclose(np.asarray(got.numpy()), want,
                               rtol=2e-4, atol=2e-4)


def test_t5_training_with_ignore_index_labels():
    """-100-padded labels train without feeding garbage decoder inputs
    (the _shift_right masking contract)."""
    from paddle_tpu.models import T5ForConditionalGeneration, t5_tiny
    paddle.seed(0)
    m = T5ForConditionalGeneration(t5_tiny(dropout_rate=0.0))
    rng = np.random.RandomState(0)
    src = paddle.to_tensor(rng.randint(2, 128, (2, 8)).astype(np.int64))
    lab = rng.randint(2, 128, (2, 6)).astype(np.int64)
    lab[:, -2:] = -100                        # padded tail
    loss, _ = m(src, labels=paddle.to_tensor(lab))
    assert np.isfinite(float(loss.numpy()))
    loss.backward()
    assert all(np.isfinite(np.asarray(p.grad.numpy())).all()
               for p in m.parameters() if p.grad is not None)
