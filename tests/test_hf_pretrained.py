"""from_pretrained over local HF checkpoints: logits parity vs the
transformers (torch CPU) forward on the same weights — the strongest
possible oracle for the model families (reference: PaddleNLP
from_pretrained + its HF interop)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import LlamaForCausalLM as PTLlama

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


@pytest.fixture(scope="module")
def hf_llama_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("hf_llama")
    cfg = transformers.LlamaConfig(
        vocab_size=96, hidden_size=32, intermediate_size=88,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-5, tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(cfg)
    hf.eval()
    hf.save_pretrained(d)
    return str(d), hf


def test_llama_logits_match_transformers(hf_llama_dir):
    d, hf = hf_llama_dir
    model = PTLlama.from_pretrained(d)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 96, (2, 10)).astype(np.int64)

    with torch.no_grad():
        want = hf(torch.tensor(ids)).logits.float().numpy()
    model.eval()
    got = model(paddle.to_tensor(ids))
    if isinstance(got, tuple):
        got = got[0]
    np.testing.assert_allclose(np.asarray(got.numpy()), want,
                               rtol=2e-4, atol=2e-4)


def test_llama_generate_greedy_matches_transformers(hf_llama_dir):
    d, hf = hf_llama_dir
    model = PTLlama.from_pretrained(d)
    model.eval()
    rng = np.random.RandomState(1)
    ids = rng.randint(0, 96, (1, 6)).astype(np.int64)
    with torch.no_grad():
        want = hf.generate(torch.tensor(ids), max_new_tokens=8,
                           do_sample=False).numpy()
    got = model.generate(paddle.to_tensor(ids), max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(got.numpy()), want)


def test_gpt2_weights_map(tmp_path):
    cfg = transformers.GPT2Config(
        vocab_size=80, n_positions=32, n_embd=24, n_layer=2, n_head=3)
    torch.manual_seed(0)
    hf = transformers.GPT2LMHeadModel(cfg)
    hf.eval()       # GPT-2 defaults to 0.1 dropout — train mode would
    hf.save_pretrained(tmp_path)   # make the oracle nondeterministic

    from paddle_tpu.models import GPTForCausalLM, GPTConfig
    from paddle_tpu.models.pretrained import load_gpt_from_hf
    model = GPTForCausalLM(GPTConfig(
        vocab_size=80, hidden_size=24, num_hidden_layers=2,
        num_attention_heads=3, max_position_embeddings=32))
    load_gpt_from_hf(model, str(tmp_path))

    rng = np.random.RandomState(2)
    ids = rng.randint(0, 80, (2, 8)).astype(np.int64)
    with torch.no_grad():
        want = hf(torch.tensor(ids)).logits.float().numpy()
    model.eval()
    got = model(paddle.to_tensor(ids))
    if isinstance(got, tuple):
        got = got[0]
    np.testing.assert_allclose(np.asarray(got.numpy()), want,
                               rtol=2e-4, atol=2e-4)


def test_bert_logits_match_transformers(tmp_path):
    cfg = transformers.BertConfig(
        vocab_size=120, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=48, type_vocab_size=2)
    torch.manual_seed(0)
    hf = transformers.BertModel(cfg)
    hf.eval()
    hf.save_pretrained(tmp_path)

    from paddle_tpu.models import BertModel
    from paddle_tpu.models.pretrained import (bert_config_from_hf,
                                              load_bert_from_hf)
    model = BertModel(bert_config_from_hf(str(tmp_path)))
    load_bert_from_hf(model, str(tmp_path))
    model.eval()

    rng = np.random.RandomState(3)
    ids = rng.randint(0, 120, (2, 9)).astype(np.int64)
    with torch.no_grad():
        out = hf(torch.tensor(ids))
        want_seq = out.last_hidden_state.numpy()
        want_pooled = out.pooler_output.numpy()
    got = model(paddle.to_tensor(ids))
    got_seq, got_pooled = (got if isinstance(got, tuple) else (got, None))
    np.testing.assert_allclose(np.asarray(got_seq.numpy()), want_seq,
                               rtol=2e-4, atol=2e-4)
    if got_pooled is not None:
        np.testing.assert_allclose(np.asarray(got_pooled.numpy()),
                                   want_pooled, rtol=2e-4, atol=2e-4)
