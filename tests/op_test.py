"""OpTest — the systematic per-op parity harness (reference:
``test/legacy_test/op_test.py`` — SURVEY.md §4 calls its dual-mode
``check_output`` + numeric ``check_grad`` "the single most important harness
to replicate").

A case declares an op once; the harness then checks, per dtype:

1. **check_output** — eager op output vs a numpy reference;
2. **static parity** — the op under ``@paddle.jit.to_static`` (i.e. traced
   through jax.jit) vs its eager output — the reference's dygraph/static
   dual-mode contract;
3. **check_grad** — tape-analytic gradient of ``sum(op(x) * w)`` vs central
   finite differences on sampled coordinates (reference check_grad's
   ``max_relative_error`` criterion).

Declarative usage (see ``test_op_suite.py``)::

    OpCase("tanh", lambda: dict(x=randn(3, 4)), ref=np.tanh, grad=True)
"""
from __future__ import annotations

import numpy as np

import paddle_tpu as paddle

_RNG = np.random.RandomState(1234)


def randn(*shape):
    return _RNG.randn(*shape).astype(np.float32)


def randpos(*shape, lo=0.1, hi=2.0):
    return _RNG.uniform(lo, hi, shape).astype(np.float32)


def randu(*shape, lo=-1.0, hi=1.0):
    return _RNG.uniform(lo, hi, shape).astype(np.float32)


def randint(*shape, lo=0, hi=10):
    return _RNG.randint(lo, hi, shape).astype(np.int64)


class OpCase:
    """One op's test declaration.

    op        — name resolvable on ``paddle`` (dots allowed: "linalg.inv")
                or a callable taking Tensors.
    make      — () -> dict of named inputs (np arrays; non-array values are
                passed through as python scalars/kwargs).
    ref       — callable on the numpy inputs returning the expected output
                (or tuple of outputs); None skips the value check (shape/
                finiteness only).
    grad      — check_grad over the float inputs.
    grad_vars — subset of input names to grad-check (default: all floats).
    kwargs    — extra non-tensor kwargs for the op.
    rtol/atol — output tolerances; gtol — max relative error for grads
                (reference check_grad's max_relative_error).
    static    — also run under to_static and compare to eager.
    names     — aliases also exercised (op() called via each name).
    """

    def __init__(self, op, make, ref=None, grad=False, grad_vars=None,
                 kwargs=None, rtol=1e-5, atol=1e-6, gtol=5e-2, static=True,
                 eps=1e-3, name=None):
        self.op = op
        self.make = make
        self.ref = ref
        self.grad = grad
        self.grad_vars = grad_vars
        self.kwargs = kwargs or {}
        self.rtol, self.atol, self.gtol = rtol, atol, gtol
        self.static = static
        self.eps = eps
        self.name = name or (op if isinstance(op, str) else op.__name__)

    # -- resolution ----------------------------------------------------------
    def _fn(self):
        if callable(self.op):
            return self.op
        obj = paddle
        for part in self.op.split("."):
            obj = getattr(obj, part)
        return obj

    @staticmethod
    def _wrap(v, differentiable=False):
        if isinstance(v, np.ndarray):
            t = paddle.to_tensor(v)
            if differentiable and np.issubdtype(v.dtype, np.floating):
                t.stop_gradient = False
            return t
        if isinstance(v, (list, tuple)) and v and \
                all(isinstance(e, np.ndarray) for e in v):
            return type(v)(OpCase._wrap(e, differentiable) for e in v)
        return v

    @staticmethod
    def _unwrap(out):
        if isinstance(out, (list, tuple)):
            return type(out)(OpCase._unwrap(o) for o in out)
        return out.numpy() if hasattr(out, "numpy") else np.asarray(out)

    def _call(self, inputs, differentiable=False):
        fn = self._fn()
        tensors = {k: self._wrap(v, differentiable) for k, v in inputs.items()}
        out = fn(**tensors, **self.kwargs)
        return out, tensors

    # -- the three checks ----------------------------------------------------
    def check_output(self):
        inputs = self.make()
        out, _ = self._call(inputs)
        got = self._unwrap(out)
        if self.ref is not None:
            try:
                want = self.ref(**inputs)
            except TypeError:      # numpy refs use their own param names
                want = self.ref(*inputs.values())
            self._assert_close(got, want, self.rtol, self.atol,
                               f"{self.name}: eager vs numpy ref")
        else:
            for g in (got if isinstance(got, (list, tuple)) else [got]):
                assert np.all(np.isfinite(np.asarray(g, np.float64))) or \
                    not np.issubdtype(np.asarray(g).dtype, np.floating), \
                    f"{self.name}: non-finite output"
        if self.static:
            self._check_static(inputs, got)
        return got

    def _check_static(self, inputs, eager_out):
        fn = self._fn()
        arr_keys = [k for k, v in inputs.items() if isinstance(v, np.ndarray)]
        passthrough = {k: v for k, v in inputs.items()
                       if not isinstance(v, np.ndarray)}

        @paddle.jit.to_static
        def static_fn(*args):
            named = dict(zip(arr_keys, args))
            wrapped_pt = {k: self._wrap(v) for k, v in passthrough.items()}
            return fn(**named, **wrapped_pt, **self.kwargs)

        s_out = static_fn(*[paddle.to_tensor(inputs[k]) for k in arr_keys])
        self._assert_close(self._unwrap(s_out), eager_out, self.rtol,
                           self.atol, f"{self.name}: to_static vs eager")

    def check_grad(self):
        inputs = self.make()
        float_keys = [k for k, v in inputs.items()
                      if isinstance(v, np.ndarray)
                      and np.issubdtype(v.dtype, np.floating)]
        keys = self.grad_vars or float_keys

        out, tensors = self._call(inputs, differentiable=True)
        outs = out if isinstance(out, (list, tuple)) else [out]
        outs = [o for o in outs if hasattr(o, "numpy")
                and np.issubdtype(np.asarray(o.numpy()).dtype, np.floating)]
        ws = [np.asarray(_RNG.randn(*o.shape), np.float32) for o in outs]
        loss = None
        for o, w in zip(outs, ws):
            term = (o * paddle.to_tensor(w)).sum()
            loss = term if loss is None else loss + term
        loss.backward()

        def scalar_f(**np_inputs):
            o2, _ = self._call(np_inputs)
            o2 = o2 if isinstance(o2, (list, tuple)) else [o2]
            o2 = [o for o in o2 if hasattr(o, "numpy")
                  and np.issubdtype(np.asarray(o.numpy()).dtype, np.floating)]
            return float(sum((np.asarray(o.numpy(), np.float64) * w).sum()
                             for o, w in zip(o2, ws)))

        for k in keys:
            analytic = tensors[k].grad
            assert analytic is not None, f"{self.name}: no grad for '{k}'"
            analytic = np.asarray(analytic.numpy(), np.float64)
            base = inputs[k]
            flat = base.reshape(-1)
            n = flat.size
            coords = (np.arange(n) if n <= 16
                      else np.linspace(0, n - 1, 16).astype(int))
            for c in coords:
                pert = dict(inputs)
                bumped = base.copy().reshape(-1)
                bumped[c] += self.eps
                pert[k] = bumped.reshape(base.shape)
                f_hi = scalar_f(**pert)
                bumped[c] -= 2 * self.eps
                pert[k] = bumped.reshape(base.shape)
                f_lo = scalar_f(**pert)
                numeric = (f_hi - f_lo) / (2 * self.eps)
                a = analytic.reshape(-1)[c]
                denom = max(abs(numeric), abs(a), 1.0)
                assert abs(a - numeric) / denom <= self.gtol, (
                    f"{self.name}: grad mismatch for '{k}'[{c}]: "
                    f"analytic={a:.6g} numeric={numeric:.6g}")

    @staticmethod
    def _assert_close(got, want, rtol, atol, msg):
        if isinstance(want, (list, tuple)):
            assert isinstance(got, (list, tuple)) and len(got) == len(want), \
                f"{msg}: structure mismatch"
            for g, w in zip(got, want):
                OpCase._assert_close(g, w, rtol, atol, msg)
            return
        got = np.asarray(got)
        want = np.asarray(want)
        if want.dtype == bool or np.issubdtype(want.dtype, np.integer):
            np.testing.assert_array_equal(got, want, err_msg=msg)
        else:
            np.testing.assert_allclose(got, want.astype(got.dtype), rtol=rtol,
                                       atol=atol, err_msg=msg)

    def run(self):
        self.check_output()
        if self.grad:
            self.check_grad()
