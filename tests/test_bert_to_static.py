"""Config-2 exit criterion (SURVEY.md §7.1 M1, BASELINE.json configs[1]):
BERT/ERNIE-base fine-tune through ``@to_static`` — the dygraph↔static
parity contract, with the compiled path actually taken (no graph-break
fallback)."""
import warnings

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models.bert import (BertForSequenceClassification,
                                    ErnieForSequenceClassification,
                                    ErnieConfig, bert_tiny)


def _data(cfg, batch=8, seq=32, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, cfg.vocab_size, (batch, seq))
    labels = rng.integers(0, cfg.num_labels, (batch,))
    mask = np.ones((batch, seq), np.int64)
    mask[:, seq // 2:] = 0
    return (paddle.to_tensor(ids), paddle.to_tensor(labels),
            paddle.to_tensor(mask))


def _finetune(model, ids, labels, mask, steps=6, static=False):
    fwd = paddle.jit.to_static(model) if static else model
    opt = paddle.optimizer.AdamW(learning_rate=5e-4,
                                 parameters=model.parameters())
    losses = []
    for _ in range(steps):
        loss, _ = fwd(ids, attention_mask=mask, labels=labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    return losses


def test_bert_finetune_to_static_matches_eager():
    cfg = bert_tiny()
    paddle.seed(3)
    eager = BertForSequenceClassification(cfg)
    paddle.seed(3)
    static = BertForSequenceClassification(cfg)
    static.set_state_dict(eager.state_dict())
    # dropout must be deterministic across both paths for exact parity
    eager.eval()
    static.eval()
    ids, labels, mask = _data(cfg)
    with warnings.catch_warnings():
        warnings.simplefilter("error")      # a graph break fails the test
        l_static = _finetune(static, ids, labels, mask, static=True)
    l_eager = _finetune(eager, ids, labels, mask, static=False)
    np.testing.assert_allclose(l_static, l_eager, rtol=2e-4, atol=2e-5)
    assert l_static[-1] < l_static[0], l_static
    sf = static.forward
    assert all(not e["fallback"] for e in sf._cache.values())


def test_ernie_finetune_to_static_learns():
    cfg = ErnieConfig(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                      num_attention_heads=4, intermediate_size=128,
                      max_position_embeddings=64)
    paddle.seed(5)
    model = ErnieForSequenceClassification(cfg)
    model.eval()
    ids, labels, mask = _data(cfg)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        losses = _finetune(model, ids, labels, mask, steps=8, static=True)
    assert losses[-1] < losses[0] * 0.9, losses
