"""3-D hybrid parallelism on one mesh: dp=2 x pp=2 x mp=2 — the
BASELINE.json config-4 shape (GPT-1.3B-class dp+mp+pp). The pipeline
engine shard_maps only the pp axis; dp/mp stay in GSPMD auto mode, so
data sharded over dp and block weights sharded over mp compose with the
ppermute schedule in ONE jitted program. Parity contract: identical loss
and gradients vs the sequential single-device oracle (the
hybrid_parallel_* loss-parity pattern of test/collective/fleet)."""
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.engine import PipelinedModule, stacked_fsdp_spec
from paddle_tpu.models import LlamaForCausalLMPipe, llama_tiny
from paddle_tpu.models.llama import LlamaPretrainingCriterion
from paddle_tpu.framework.functional import FunctionalModule


def _stacked_mp_spec(arr):
    """[n_chunks, lpc, *param] block leaf -> pp on dim 0, mp on the last
    dim of 2-D weights (column-parallel placement; GSPMD completes the
    rest)."""
    if arr.ndim >= 4:           # stacked linear weight [S, lpc, in, out]
        return P("pp", *([None] * (arr.ndim - 2)), "mp")
    return P("pp")


def test_dp_mp_pp_matches_oracle():
    paddle.seed(7)
    cfg = llama_tiny(num_hidden_layers=4)
    pipe = LlamaForCausalLMPipe(cfg, num_stages=2)
    mesh = mesh_mod.init_mesh({"dp": 2, "pp": 2, "mp": 2})
    try:
        pm = PipelinedModule(pipe)
        rng = np.random.default_rng(0)
        batch, seq, n_micro = 8, 16, 4
        ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)),
                          jnp.int32)
        labels = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)),
                             jnp.int32)
        key = jax.random.PRNGKey(0)
        crit = FunctionalModule(LlamaPretrainingCriterion())

        edge, stacked = pm.edge_arrays(), pm.stacked_arrays()

        # ---- oracle: sequential apply on replicated arrays
        def oracle_loss(e, s):
            h = pm._fm_pre(e, [], key, ids)[0]
            flat = [a.reshape((-1,) + tuple(a.shape[2:])) for a in s]
            for i in range(len(pm.blocks)):
                h, _ = pm._fm_blk([a[i] for a in flat], [], key, h)
            logits = pm._fm_post(e, [], key, h)[0]
            return crit([], [], key, logits, labels)[0]

        o_loss, (o_ge, o_gs) = jax.value_and_grad(
            oracle_loss, argnums=(0, 1))(edge, stacked)

        # ---- 3D: pp-stacked + mp-column weights + dp-sharded microbatches
        s_sharded = [jax.device_put(a, NamedSharding(mesh,
                                                     _stacked_mp_spec(a)))
                     for a in stacked]
        e_sharded = [jax.device_put(a, NamedSharding(mesh, P()))
                     for a in edge]
        mb = batch // n_micro
        mx = ids.reshape((n_micro, mb, seq))
        mx = jax.device_put(mx, NamedSharding(mesh, P(None, "dp")))

        @jax.jit
        def hybrid_step(e, s):
            def loss_fn(ee, ss):
                out = pm(ee, ss, mx)
                logits = out.reshape((-1,) + tuple(out.shape[2:]))
                return crit([], [], key, logits, labels)[0]
            return jax.value_and_grad(loss_fn, argnums=(0, 1))(e, s)

        with mesh:
            h_loss, (h_ge, h_gs) = hybrid_step(e_sharded, s_sharded)

        np.testing.assert_allclose(float(h_loss), float(o_loss),
                                   rtol=2e-5, atol=2e-5)
        for a, b in zip(h_ge, o_ge):
            np.testing.assert_allclose(np.asarray(jax.device_get(a)),
                                       np.asarray(b), rtol=2e-4, atol=2e-5)
        for a, b in zip(h_gs, o_gs):
            np.testing.assert_allclose(np.asarray(jax.device_get(a)),
                                       np.asarray(b), rtol=2e-4, atol=2e-5)
        # the mp sharding actually took: column dim split across mp
        big = max(s_sharded, key=lambda a: a.ndim)
        assert any(sh.shape[-1] < big.shape[-1]
                   for sh in [s.data for s in big.addressable_shards]), \
            "block weights were not mp-sharded"
    finally:
        mesh_mod.reset_mesh()


def _edge_fsdp_spec(arr):
    """ZeRO-3 for the unstacked edge params — the PRODUCTION placement
    rule (fleet sharding.shard_spec_for), not a test re-implementation."""
    from paddle_tpu.distributed.fleet.meta_parallel.sharding import \
        shard_spec_for
    spec = shard_spec_for(arr.shape)
    return P(*spec) if spec is not None else P()


def test_dp_pp_sharding_matches_oracle():
    """VERDICT round-3 item 7: the config-4 composition gap — pp and
    ZeRO-3 'sharding' (plus dp) in ONE jitted program, for BOTH backward
    schedules (the 1F1B custom_vjp must compose with GSPMD too)."""
    paddle.seed(11)
    cfg = llama_tiny(num_hidden_layers=4)
    pipe = LlamaForCausalLMPipe(cfg, num_stages=2)
    mesh = mesh_mod.init_mesh({"dp": 2, "pp": 2, "sharding": 2})
    try:
        rng = np.random.default_rng(5)
        batch, seq, n_micro = 8, 16, 4
        ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)),
                          jnp.int32)
        labels = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)),
                             jnp.int32)
        key = jax.random.PRNGKey(0)
        crit = FunctionalModule(LlamaPretrainingCriterion())
        pm = PipelinedModule(pipe)
        edge, stacked = pm.edge_arrays(), pm.stacked_arrays()

        def oracle_loss(e, s):
            h = pm._fm_pre(e, [], key, ids)[0]
            flat = [a.reshape((-1,) + tuple(a.shape[2:])) for a in s]
            for i in range(len(pm.blocks)):
                h, _ = pm._fm_blk([a[i] for a in flat], [], key, h)
            logits = pm._fm_post(e, [], key, h)[0]
            return crit([], [], key, logits, labels)[0]

        o_loss, (o_ge, o_gs) = jax.value_and_grad(
            oracle_loss, argnums=(0, 1))(edge, stacked)

        s_sharded = [jax.device_put(a, NamedSharding(mesh,
                                                     stacked_fsdp_spec(a)))
                     for a in stacked]
        e_sharded = [jax.device_put(a, NamedSharding(mesh,
                                                     _edge_fsdp_spec(a)))
                     for a in edge]
        mb = batch // n_micro
        mx = jax.device_put(ids.reshape((n_micro, mb, seq)),
                            NamedSharding(mesh, P(None, "dp")))

        for schedule in ("fthenb", "1f1b"):
            pm_s = PipelinedModule(pipe, schedule=schedule)

            @jax.jit
            def hybrid_step(e, s):
                def loss_fn(ee, ss):
                    out = pm_s(ee, ss, mx)
                    logits = out.reshape((-1,) + tuple(out.shape[2:]))
                    return crit([], [], key, logits, labels)[0]
                return jax.value_and_grad(loss_fn, argnums=(0, 1))(e, s)

            with mesh:
                h_loss, (h_ge, h_gs) = hybrid_step(e_sharded, s_sharded)
            np.testing.assert_allclose(float(h_loss), float(o_loss),
                                       rtol=2e-5, atol=2e-5,
                                       err_msg=schedule)
            for a, b in zip(h_ge, o_ge):
                np.testing.assert_allclose(np.asarray(jax.device_get(a)),
                                           np.asarray(b), rtol=2e-4,
                                           atol=2e-5, err_msg=schedule)
            for a, b in zip(h_gs, o_gs):
                np.testing.assert_allclose(np.asarray(jax.device_get(a)),
                                           np.asarray(b), rtol=2e-4,
                                           atol=2e-5, err_msg=schedule)
        # ZeRO-3 actually took: block weights split over 'sharding' at rest
        big = max(s_sharded, key=lambda a: a.ndim)
        assert any(sh.shape[2] < big.shape[2]
                   for sh in [s.data for s in big.addressable_shards]), \
            "block weights were not fsdp-sharded at rest"
    finally:
        mesh_mod.reset_mesh()
