"""1F1B-memory pipeline schedule (VERDICT.md round-3 item 3; reference:
``pipeline_scheduler_pass`` 1F1B + ``fleet/meta_parallel/
pipeline_parallel.py`` steady-state memory contract).

``schedule='1f1b'`` swaps the engine's backward from jax.grad-through-scan
(which saves every tick's stage residuals — GPipe memory, O(M·S)) to an
explicit interleaved recompute/backward scan holding at most ``2S-1``
stage-input activations (O(S), independent of M). Gradients must be exact
— rematerialisation changes memory, never math — and the compiled peak
temp memory must actually drop at M >> S.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.engine import _chunk_key, pipeline_forward


def _stage(params, x):
    w1, b1, w2, b2 = params
    h = jax.nn.gelu(x @ w1 + b1)
    return jnp.tanh(h @ w2 + b2) + x


def _stoch_stage(params, x, key):
    w1, b1, w2, b2 = params
    keep = jax.random.bernoulli(key, 0.8, x.shape)
    h = jax.nn.gelu(x @ w1 + b1)
    return (jnp.tanh(h @ w2 + b2) + x) * keep


def _setup(n_chunks=4, n_micro=8, mb=2, d=8, hidden=16, seed=0):
    rng = np.random.default_rng(seed)
    params = (
        jnp.asarray(rng.normal(size=(n_chunks, d, hidden)) * 0.3, jnp.float32),
        jnp.asarray(rng.normal(size=(n_chunks, hidden)) * 0.1, jnp.float32),
        jnp.asarray(rng.normal(size=(n_chunks, hidden, d)) * 0.3, jnp.float32),
        jnp.asarray(rng.normal(size=(n_chunks, d)) * 0.1, jnp.float32),
    )
    micro = jnp.asarray(rng.normal(size=(n_micro, mb, d)), jnp.float32)
    return params, micro


def _sequential(params, micro, base_key=None):
    out = []
    for m in range(micro.shape[0]):
        x = micro[m]
        for c in range(params[0].shape[0]):
            p = tuple(a[c] for a in params)
            if base_key is None:
                x = _stage(p, x)
            else:
                x = _stoch_stage(p, x, _chunk_key(base_key, m, c))
        out.append(x)
    return jnp.stack(out)


def test_1f1b_forward_matches_sequential():
    mesh_mod.init_mesh({"pp": 4, "dp": 2})
    try:
        params, micro = _setup()
        out = jax.jit(lambda p, x: pipeline_forward(
            _stage, p, x, schedule="1f1b"))(params, micro)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_sequential(params, micro)),
                                   rtol=1e-5, atol=1e-5)
    finally:
        mesh_mod.reset_mesh()


def test_1f1b_grads_match_fthenb_and_oracle():
    mesh_mod.init_mesh({"pp": 4, "dp": 2})
    try:
        params, micro = _setup()
        g = jnp.asarray(np.random.default_rng(5).normal(size=micro.shape),
                        jnp.float32)

        def loss(p, x, sched):
            return jnp.sum(pipeline_forward(_stage, p, x,
                                            schedule=sched) * g)

        g1, gx1 = jax.jit(jax.grad(lambda p, x: loss(p, x, "1f1b"),
                                   argnums=(0, 1)))(params, micro)
        g0, gx0 = jax.jit(jax.grad(lambda p, x: loss(p, x, "fthenb"),
                                   argnums=(0, 1)))(params, micro)
        gs, gxs = jax.grad(lambda p, x: jnp.sum(_sequential(p, x) * g),
                           argnums=(0, 1))(params, micro)
        for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g0)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)
        for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(gs)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gx1), np.asarray(gx0),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gx1), np.asarray(gxs),
                                   rtol=1e-4, atol=1e-5)
    finally:
        mesh_mod.reset_mesh()


def test_1f1b_dropout_grads_match_sequential():
    """Recompute must replay the SAME per-(micro, chunk) dropout mask."""
    mesh_mod.init_mesh({"pp": 4, "dp": 2})
    try:
        params, micro = _setup(n_micro=6)
        base = jax.random.key(11)
        g = jnp.asarray(np.random.default_rng(7).normal(size=micro.shape),
                        jnp.float32)

        def loss_pipe(p):
            return jnp.sum(pipeline_forward(_stoch_stage, p, micro,
                                            rng_key=base,
                                            schedule="1f1b") * g)

        def loss_seq(p):
            return jnp.sum(_sequential(p, micro, base) * g)

        gp = jax.jit(jax.grad(loss_pipe))(params)
        gs = jax.grad(loss_seq)(params)
        for a, b in zip(jax.tree.leaves(gp), jax.tree.leaves(gs)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)
    finally:
        mesh_mod.reset_mesh()


def test_1f1b_rejects_vpp():
    mesh_mod.init_mesh({"pp": 4, "dp": 2})
    try:
        params, micro = _setup(n_chunks=8)
        with pytest.raises(ValueError, match="vpp"):
            pipeline_forward(_stage, params, micro, vpp_degree=2,
                             schedule="1f1b")
    finally:
        mesh_mod.reset_mesh()


def test_pytree_activations_both_schedules():
    """VERDICT round-3 weak item 3: the activation contract widens from
    one array to any pytree (e.g. (hidden, gate-state) pairs) — uniform
    across stages, like the reference's tensor-meta contract per run."""
    mesh_mod.init_mesh({"pp": 4, "dp": 2})
    try:
        params, micro = _setup()
        micro2 = {"h": micro, "aux": micro * 0.5}

        def tree_stage(p, x):
            h = _stage(p, x["h"]) + x["aux"]
            return {"h": h, "aux": jnp.tanh(x["aux"])}

        def seq(p, xt):
            outs = {"h": [], "aux": []}
            for m in range(micro.shape[0]):
                x = {"h": xt["h"][m], "aux": xt["aux"][m]}
                for c in range(p[0].shape[0]):
                    x = tree_stage(tuple(a[c] for a in p), x)
                outs["h"].append(x["h"])
                outs["aux"].append(x["aux"])
            return {k: jnp.stack(v) for k, v in outs.items()}

        want = seq(params, micro2)
        g = jnp.asarray(np.random.default_rng(3).normal(size=micro.shape),
                        jnp.float32)
        for sched in ("fthenb", "1f1b"):
            out = jax.jit(lambda p, x: pipeline_forward(
                tree_stage, p, x, schedule=sched))(params, micro2)
            for k in ("h", "aux"):
                np.testing.assert_allclose(np.asarray(out[k]),
                                           np.asarray(want[k]),
                                           rtol=1e-5, atol=1e-5,
                                           err_msg=f"{sched}:{k}")

            def loss(p, x, s=sched):
                o = pipeline_forward(tree_stage, p, x, schedule=s)
                return jnp.sum(o["h"] * g) + jnp.sum(o["aux"])

            gp = jax.jit(jax.grad(loss))(params, micro2)
            gs = jax.grad(lambda p, x: jnp.sum(seq(p, x)["h"] * g)
                          + jnp.sum(seq(p, x)["aux"]))(params, micro2)
            for a, b in zip(jax.tree.leaves(gp), jax.tree.leaves(gs)):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-4, atol=1e-5,
                                           err_msg=sched)
    finally:
        mesh_mod.reset_mesh()


def test_1f1b_peak_memory_below_fthenb():
    """The schedule's whole point: at M=8, S=4 the compiled train step's
    temp allocation (activation residuals) must be materially smaller
    under 1f1b than under the default backward (VERDICT round-3 item 3
    asks for exactly this ``memory_analysis`` comparison)."""
    mesh_mod.init_mesh({"pp": 4, "dp": 2})
    try:
        # big-ish stage so residuals dominate: d=64, hidden=256, mb=4
        params, micro = _setup(n_chunks=4, n_micro=8, mb=4, d=64, hidden=256)

        def make_loss(sched):
            def loss(p, x):
                return jnp.sum(pipeline_forward(_stage, p, x,
                                                schedule=sched) ** 2)
            return jax.jit(jax.grad(loss))

        sizes = {}
        for sched in ("fthenb", "1f1b"):
            compiled = make_loss(sched).lower(params, micro).compile()
            ma = compiled.memory_analysis()
            assert ma is not None, "memory_analysis unavailable"
            sizes[sched] = int(ma.temp_size_in_bytes)
        # require a real gap, not noise: 1f1b's temp must be < 60% of
        # fthenb's (M=8 residual sets vs a 2S-1=7-slot input ring; the
        # ratio widens further with M and layers-per-chunk)
        assert sizes["1f1b"] < 0.6 * sizes["fthenb"], sizes
    finally:
        mesh_mod.reset_mesh()
