"""Parameter-server mode (reference: ``paddle/fluid/distributed/ps/`` +
``the_one_ps.py``; test model: reference ``test/ps/`` + the sparse
table unit tests). Servers run as in-process threads — the RPC tier is
real sockets either way, and SURVEY §4 takeaway 4 prefers the
single-process simulator for CI."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.ps import (DistributedEmbedding, PSClient,
                                       PSServer, SparseTable)


def _servers(n=2):
    srvs = [PSServer().start() for _ in range(n)]
    client = PSClient([s.endpoint for s in srvs])
    return srvs, client


def _stop(srvs, client):
    client.shutdown_servers()
    client.close()
    for s in srvs:
        s.stop()


def test_sparse_table_adagrad_math():
    t = SparseTable(dim=4, optimizer="adagrad", lr=0.1, initializer="zeros")
    keys = np.array([7, 3, 7], np.int64)
    g = np.ones((3, 4), np.float32)
    t.push_grad(keys, g)
    # duplicate key 7 dedups to a summed grad of 2
    rows = t.pull(np.array([3, 7], np.int64))
    acc3, acc7 = 1.0, 4.0
    np.testing.assert_allclose(rows[0], -0.1 * 1 / (np.sqrt(acc3) + 1e-8),
                               rtol=1e-6)
    np.testing.assert_allclose(rows[1], -0.1 * 2 / (np.sqrt(acc7) + 1e-8),
                               rtol=1e-6)


def test_sparse_table_deterministic_init():
    a = SparseTable(dim=8, seed=3)
    b = SparseTable(dim=8, seed=3)
    k = np.array([123456789], np.int64)
    np.testing.assert_array_equal(a.pull(k), b.pull(k))
    assert np.abs(a.pull(k)).max() <= 0.01


def test_rpc_pull_push_roundtrip(tmp_path):
    srvs, client = _servers(2)
    try:
        client.create_table(0, dim=4, optimizer="sgd", lr=1.0,
                            initializer="zeros")
        keys = np.arange(10, dtype=np.int64)          # spans both shards
        rows = client.pull(0, keys)
        np.testing.assert_array_equal(rows, np.zeros((10, 4)))
        client.push_grad(0, keys, np.full((10, 4), 0.5, np.float32))
        np.testing.assert_allclose(client.pull(0, keys),
                                   np.full((10, 4), -0.5))
        # keys return in request order regardless of shard interleave
        perm = np.array([9, 0, 5, 2], np.int64)
        np.testing.assert_allclose(client.pull(0, perm),
                                   np.full((4, 4), -0.5))
        stats = client.stats(0)
        assert stats["0"] == 5                         # evens on shard 0
        client.save(0, str(tmp_path / "table0"))
        client.push_grad(0, keys, np.full((10, 4), 1.0, np.float32))
        client.load(0, str(tmp_path / "table0"))
        np.testing.assert_allclose(client.pull(0, keys),
                                   np.full((10, 4), -0.5))
    finally:
        _stop(srvs, client)


def test_distributed_embedding_sync_parity_with_local():
    """Sync SGD through the PS must match a trainer-local dense embedding
    update exactly (reference semantic: sparse_embedding == embedding when
    world=1, sync)."""
    srvs, client = _servers(2)
    try:
        emb = DistributedEmbedding(8, client, mode="sync", optimizer="sgd",
                                   learning_rate=0.1, initializer="zeros")
        w = paddle.to_tensor(np.zeros((16, 8), np.float32),
                             stop_gradient=False)
        ids_np = np.array([[1, 3], [3, 5]], np.int64)
        for _ in range(3):
            ids = paddle.to_tensor(ids_np)
            out = emb(ids)
            loss = (out * out + 2.0 * out).sum()
            loss.backward()
            # local oracle: same loss on the dense table
            w.clear_gradient() if w.grad is not None else None
            lw = w[paddle.to_tensor(ids_np.reshape(-1))].reshape([2, 2, 8])
            lloss = (lw * lw + 2.0 * lw).sum()
            lloss.backward()
            with paddle.no_grad():
                w -= 0.1 * w.grad
            w.stop_gradient = False
            w.grad = None
        pulled = client.pull(emb.table_id, np.array([1, 3, 5], np.int64))
        np.testing.assert_allclose(pulled,
                                   w.numpy()[np.array([1, 3, 5])],
                                   rtol=1e-5, atol=1e-6)
    finally:
        _stop(srvs, client)


@pytest.mark.parametrize("mode", ["async", "geo"])
def test_ctr_model_trains(mode):
    """Tiny CTR tower: sparse ids -> PS embedding -> mean pool -> dense ->
    logit; BCE drops by >40% over 40 steps in both async and geo modes."""
    srvs, client = _servers(2)
    try:
        paddle.seed(7)
        emb = DistributedEmbedding(16, client, mode=mode,
                                   learning_rate=2.0, geo_k=4,
                                   optimizer="sgd")
        dense = paddle.nn.Linear(16, 1)
        opt = paddle.optimizer.SGD(learning_rate=1.0,
                                   parameters=dense.parameters())
        rng = np.random.default_rng(0)
        ids_np = rng.integers(0, 50, (64, 5))
        # learnable rule: click iff feature-id sum is large
        y_np = (ids_np.sum(1) > 125).astype(np.float32)
        losses = []
        for _ in range(40):
            ids = paddle.to_tensor(ids_np)
            y = paddle.to_tensor(y_np.reshape(-1, 1))
            pooled = emb(ids).mean(axis=1)
            logit = dense(pooled)
            loss = paddle.nn.functional.binary_cross_entropy_with_logits(
                logit, y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        client.flush()
        assert losses[-1] < 0.6 * losses[0], losses[::8]
    finally:
        _stop(srvs, client)


def test_geo_deltas_reach_server():
    srvs, client = _servers(1)
    try:
        emb = DistributedEmbedding(4, client, mode="geo", geo_k=2,
                                   learning_rate=1.0, initializer="zeros")
        ids = paddle.to_tensor(np.array([[2]], np.int64))
        for _ in range(2):                        # geo_k pushes on step 2
            out = emb(ids)
            out.sum().backward()
        server_rows = client.pull(emb.table_id, np.array([2], np.int64))
        np.testing.assert_allclose(server_rows, -2.0 * np.ones((1, 4)),
                                   atol=1e-6)
    finally:
        _stop(srvs, client)


def test_fleet_ps_lifecycle(monkeypatch):
    """fleet.init(is_collective=False) role wiring end-to-end: a PSERVER
    role serves in a thread; a TRAINER role pulls/pushes through
    fleet.init_worker(); stop_worker() shuts the server down."""
    import threading

    from paddle_tpu.distributed import fleet

    srv_port = PSServer()                  # reserve an ephemeral port
    ep = srv_port.endpoint
    srv_port.stop()

    monkeypatch.setenv("PADDLE_PSERVERS_IP_PORT_LIST", ep)
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "1")

    monkeypatch.setenv("TRAINING_ROLE", "PSERVER")
    host, port = ep.rsplit(":", 1)
    monkeypatch.setenv("POD_IP", host)
    monkeypatch.setenv("PADDLE_PORT", port)
    fleet.init(fleet.PaddleCloudRoleMaker(is_collective=False))
    assert fleet.is_server() and not fleet.is_worker()
    fleet.init_server()
    t = threading.Thread(target=fleet.run_server, daemon=True)
    t.start()

    monkeypatch.setenv("TRAINING_ROLE", "TRAINER")
    fleet.init(fleet.PaddleCloudRoleMaker(is_collective=False))
    assert fleet.is_worker()
    client = fleet.init_worker()
    client.create_table(5, dim=2, initializer="zeros", optimizer="sgd",
                        lr=1.0)
    client.push_grad(5, np.array([1], np.int64),
                     np.ones((1, 2), np.float32))
    np.testing.assert_allclose(client.pull(5, np.array([1], np.int64)),
                               [[-1.0, -1.0]])
    fleet.stop_worker()
    t.join(timeout=10)
    assert not t.is_alive()
