"""Op schema registry (L0 codegen analogue): the derived registry is
consistent with the live op surface and the committed export is fresh."""
import os

import paddle_tpu as paddle
from paddle_tpu.ops import schema

ROOT = os.path.join(os.path.dirname(__file__), "..")


def test_registry_covers_surface():
    reg = schema.build_registry()
    s = schema.summary(reg)
    assert s["total_ops"] >= 450          # round-3 surface
    assert s["tensor_methods"] >= 200
    # spot-check: every registered op resolves on its user-facing
    # namespace (module key -> paddle.<ns>)
    ns = {"linalg": paddle.linalg, "fft": paddle.fft,
          "signal": paddle.signal, "sparse": paddle.sparse,
          "geometric": paddle.geometric,
          "functional": paddle.nn.functional,
          "fused": paddle.incubate.nn.functional}
    for name, spec in reg.items():
        targets = [ns.get(m) for m in (spec.module,) + spec.aliases
                   if ns.get(m) is not None] or [paddle]
        assert any(hasattr(t, name) for t in targets) \
            or hasattr(paddle, name), f"{spec.module}.{name}"


def test_tensor_method_flags_accurate():
    reg = schema.build_registry()
    T = paddle.to_tensor([1.0])
    for name, spec in reg.items():
        if spec.tensor_method:
            assert hasattr(type(T), name), f"{name} flagged but missing"


def test_committed_yaml_is_fresh():
    """tools/gen_op_schema.py must be re-run when ops change (the
    reference's generated-code-in-sync CI check)."""
    path = os.path.join(ROOT, "paddle_tpu", "ops", "ops.yaml")
    with open(path) as f:
        committed = f.read()
    assert committed == schema.to_yaml(), (
        "ops.yaml is stale — run python tools/gen_op_schema.py")


def test_committed_backward_yaml_is_fresh():
    """backward.yaml (grad-provenance export — the reference
    backward.yaml analogue, VERDICT r3 'YAML codegen' partial) must be
    regenerated with the ops."""
    path = os.path.join(ROOT, "paddle_tpu", "ops", "backward.yaml")
    with open(path) as f:
        committed = f.read()
    assert committed == schema.backward_yaml(), (
        "backward.yaml is stale — run python tools/gen_op_schema.py")


def test_backward_yaml_contents():
    y = schema.backward_yaml()
    reg = schema.build_registry()
    # one grad record per DIFFERENTIABLE op: non-diff modules/names are
    # excluded, so the count sits strictly between the kernel-tier-only
    # floor and the full registry
    n = y.count("- backward_op:")
    assert 200 < n < len(reg) + 20
    # non-differentiable ops carry no grad record
    assert "- backward_op: argmax_grad" not in y
    assert "- backward_op: ones_grad" not in y
    # the kernel tier's hand-written rules are recorded
    assert "_flash_grad" in y and "_fake_quant_grad" in y
    assert "grad_source: custom_vjp" in y and "grad_source: jax_ad" in y
    # dispatch indirection is annotated
    assert "kernel_dispatch" in y
