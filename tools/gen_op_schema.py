"""Export the derived op schema (reference L0 codegen analogue):
writes ``paddle_tpu/ops/ops.yaml`` and ``docs/OPS.md`` from the registry
in ``paddle_tpu/ops/schema.py``. Run after adding ops; CI
(tests/test_op_schema.py) fails if the committed export is stale."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from paddle_tpu.ops import schema  # noqa: E402


def main():
    root = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
    reg = schema.build_registry()

    with open(os.path.join(root, "paddle_tpu", "ops", "ops.yaml"), "w") as f:
        f.write(schema.to_yaml(reg))

    with open(os.path.join(root, "paddle_tpu", "ops", "backward.yaml"),
              "w") as f:
        f.write(schema.backward_yaml(reg))

    s = schema.summary(reg)
    lines = ["# Op surface (generated — tools/gen_op_schema.py)", "",
             f"{s['total_ops']} public ops "
             f"({s['tensor_methods']} tensor methods, "
             f"{s['inplace_variants']} in-place variants).",
             "",
             "| op | module | signature | method | inplace |",
             "|---|---|---|---|---|"]
    for name in sorted(reg):
        sp = reg[name]
        sig = sp.signature.replace("|", "\\|")
        if len(sig) > 80:
            sig = sig[:77] + "..."
        lines.append(f"| {name} | {sp.module} | `{sig}` | "
                     f"{'x' if sp.tensor_method else ''} | "
                     f"{'x' if sp.inplace_variant else ''} |")
    os.makedirs(os.path.join(root, "docs"), exist_ok=True)
    with open(os.path.join(root, "docs", "OPS.md"), "w") as f:
        f.write("\n".join(lines) + "\n")
    print(f"exported {s['total_ops']} ops "
          f"({s['tensor_methods']} methods) -> ops.yaml, backward.yaml, docs/OPS.md")


if __name__ == "__main__":
    main()
