"""Self-audit: SURVEY.md §2 component inventory → paddle_tpu modules.

Run: python tools/check_inventory.py
Prints one line per inventory item with the implementing module(s) and
whether every listed symbol resolves. Used by CI (tests/test_inventory.py)
to keep the map honest as the build grows.
"""
from __future__ import annotations

import importlib
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# (SURVEY §2 item, module path, symbols that must resolve)
INVENTORY = [
    ("Phi kernels / op layer", "paddle_tpu.ops",
     ["add", "matmul", "einsum", "topk", "cumsum"]),
    ("Flash attention (FA2 kernels)", "paddle_tpu.ops.pallas",
     ["flash_attention", "flash_attention_with_lse", "mha_reference"]),
    ("Ring attention / CP", "paddle_tpu.ops.pallas",
     ["ring_flash_attention"]),
    ("Int8 GEMM (quant inference)", "paddle_tpu.ops.pallas",
     ["int8_matmul", "quantize_weight"]),
    ("Fused ops (phi fusion tier)", "paddle_tpu.incubate.nn.functional",
     ["fused_rotary_position_embedding", "fused_rms_norm", "swiglu"]),
    ("Eager autograd engine", "paddle_tpu.autograd.tape",
     ["apply", "run_backward", "no_grad"]),
    ("PyLayer (custom op autograd)", "paddle_tpu.autograd.pylayer",
     ["PyLayer"]),
    ("to_static / SOT tracer", "paddle_tpu.jit",
     ["to_static", "save", "load", "InputSpec"]),
    ("Static Program/Executor", "paddle_tpu.static",
     ["Program", "Executor", "BuildStrategy", "program_guard"]),
    ("Inference predictor", "paddle_tpu.inference",
     ["Config", "create_predictor"]),
    ("nn layers", "paddle_tpu.nn",
     ["Linear", "Conv2D", "LayerNorm", "BatchNorm2D", "MultiHeadAttention",
      "TransformerEncoder", "LSTM", "Embedding"]),
    ("Optimizers", "paddle_tpu.optimizer",
     ["SGD", "Momentum", "Adam", "AdamW", "Lamb", "Adagrad", "RMSProp",
      "Adadelta"]),
    ("LR schedulers", "paddle_tpu.optimizer.lr",
     ["NoamDecay", "LinearWarmup", "CosineAnnealingDecay", "OneCycleLR",
      "ReduceOnPlateau"]),
    ("AMP", "paddle_tpu.amp",
     ["auto_cast", "GradScaler", "decorate"]),
    ("AMP debugging / nan checker", "paddle_tpu.amp.debugging",
     ["check_numerics", "enable_tensor_checker", "TensorCheckerConfig"]),
    ("DataLoader / io", "paddle_tpu.io",
     ["Dataset", "IterableDataset", "DataLoader", "BatchSampler",
      "DistributedBatchSampler", "WeightedRandomSampler"]),
    ("Native shm queue (C++)", "paddle_tpu.io.native",
     ["ShmQueue", "available"]),
    ("Profiler", "paddle_tpu.profiler",
     ["Profiler", "make_scheduler", "RecordEvent", "export_chrome_tracing"]),
    ("Checkpoint save/load", "paddle_tpu.framework.io",
     ["save", "load"]),
    ("Distributed checkpoint", "paddle_tpu.distributed.checkpoint",
     ["save_state_dict", "load_state_dict", "save_group_sharded_model"]),
    ("Collectives API", "paddle_tpu.distributed",
     ["all_reduce", "all_gather", "reduce_scatter", "alltoall", "send",
      "recv", "new_group", "batch_isend_irecv"]),
    ("Mesh / topology", "paddle_tpu.distributed.mesh",
     ["init_mesh", "get_mesh", "HYBRID_AXES"]),
    ("HybridCommunicateGroup", "paddle_tpu.distributed.fleet",
     ["HybridCommunicateGroup", "CommunicateTopology"]),
    ("Fleet facade", "paddle_tpu.distributed.fleet",
     ["init", "distributed_model", "distributed_optimizer",
      "DistributedStrategy"]),
    ("TP/MP layers", "paddle_tpu.distributed.fleet.meta_parallel",
     ["ColumnParallelLinear", "RowParallelLinear", "VocabParallelEmbedding",
      "ParallelCrossEntropy", "get_rng_state_tracker"]),
    ("Pipeline (1F1B + layers)", "paddle_tpu.distributed.fleet.meta_parallel",
     ["PipelineLayer", "LayerDesc", "SharedLayerDesc", "PipelineParallel"]),
    ("SPMD pipeline engine (+VPP)", "paddle_tpu.distributed.engine",
     ["pipeline_forward", "pipeline_spmd", "pipeline_spmd_interleaved"]),
    ("Sharding stages 1-3", "paddle_tpu.distributed.sharding",
     ["group_sharded_parallel", "save_group_sharded_model"]),
    ("Sequence parallel utils",
     "paddle_tpu.distributed.fleet.utils.sequence_parallel_utils",
     ["ScatterOp", "GatherOp", "AllGatherOp", "ReduceScatterOp",
      "mark_as_sequence_parallel_parameter"]),
    ("Ring attention facade", "paddle_tpu.distributed.fleet.utils",
     ["ring_attention", "RingFlashAttention"]),
    ("Recompute", "paddle_tpu.distributed.fleet.utils", ["recompute"]),
    ("MoE / EP", "paddle_tpu.incubate.distributed.models.moe",
     ["MoELayer", "GShardGate", "SwitchGate", "NaiveGate",
      "dispatch_combine"]),
    ("Sparse-MoE LM family (Mixtral)", "paddle_tpu.models",
     ["MixtralConfig", "MixtralForCausalLM", "MixtralSparseMoeBlock",
      "mixtral_8x7b", "mixtral_tiny"]),
    ("Auto-parallel API", "paddle_tpu.distributed.auto_parallel",
     ["ProcessMesh", "Shard", "Replicate", "Partial", "shard_tensor",
      "reshard", "shard_optimizer", "Engine"]),
    ("Distributed passes", "paddle_tpu.distributed.passes",
     ["new_pass", "PassManager", "register_pass"]),
    ("Pipeline schedules (FThenB/1F1B/VPP/ZBH1)",
     "paddle_tpu.distributed.engine",
     ["pipeline_forward", "pipeline_spmd_1f1b_bwd", "pipeline_spmd_zb_bwd",
      "pipeline_spmd_interleaved", "pipeline_forward_hetero"]),
    ("DGC / LocalSGD meta-optimizers",
     "paddle_tpu.distributed.fleet.meta_optimizers",
     ["DGCMomentumOptimizer", "LocalSGDOptimizer"]),
    ("Launch CLI", "paddle_tpu.distributed.launch", ["launch_main"]),
    ("Elastic", "paddle_tpu.distributed.fleet.elastic",
     ["ElasticManager", "TrainingSupervisor", "CheckpointManager"]),
    ("Flags system", "paddle_tpu.flags",
     ["set_flags", "get_flags"]),
    ("Sparse tensors", "paddle_tpu.sparse",
     ["sparse_coo_tensor", "sparse_csr_tensor", "matmul", "masked_matmul"]),
    ("Quantization", "paddle_tpu.quantization",
     ["QuantConfig", "QAT", "PTQ", "convert"]),
    ("ASP 2:4 sparsity", "paddle_tpu.incubate.asp",
     ["prune_model", "decorate", "calculate_density"]),
    ("Higher-order AD", "paddle_tpu.incubate.autograd",
     ["jvp", "vjp", "Jacobian", "Hessian"]),
    ("hapi Model", "paddle_tpu.hapi", ["Model", "summary"]),
    ("Callbacks", "paddle_tpu.callbacks",
     ["ModelCheckpoint", "EarlyStopping", "LRScheduler"]),
    ("Metrics", "paddle_tpu.metric",
     ["Accuracy", "Precision", "Recall", "Auc"]),
    ("Vision models", "paddle_tpu.vision.models",
     ["resnet50", "vgg16", "mobilenet_v2", "LeNet"]),
    ("Vision ops (detection)", "paddle_tpu.vision.ops",
     ["nms", "roi_align", "box_iou", "distance2bbox", "yolo_box"]),
    ("Detection model (PP-YOLOE)", "paddle_tpu.models",
     ["PPYOLOE", "DetectionLoss", "ppyoloe_lite"]),
    ("LM zoo", "paddle_tpu.models",
     ["LlamaForCausalLM", "GPTForCausalLM", "BertModel", "ErnieModel"]),
    ("Generation", "paddle_tpu.models.generation",
     ["GenerationMixin", "KVCache"]),
    ("fft", "paddle_tpu.fft", ["fft", "rfft", "irfft", "fft2", "fftshift"]),
    ("signal", "paddle_tpu.signal", ["stft", "istft", "frame"]),
    ("text", "paddle_tpu.text", ["ViterbiDecoder", "viterbi_decode"]),
    ("audio", "paddle_tpu.audio",
     ["MelSpectrogram", "LogMelSpectrogram", "MFCC"]),
    ("Device API", "paddle_tpu.device",
     ["set_device", "synchronize", "Stream", "Event", "cuda"]),
    ("Profiler benchmark timer", "paddle_tpu.profiler", ["benchmark"]),
    ("utils", "paddle_tpu.utils",
     ["run_check", "get_weights_path_from_url", "try_import"]),
    ("Paged attention (serving KV)", "paddle_tpu.ops.pallas.paged_attention",
     ["paged_attention", "paged_attention_reference"]),
    ("Serving engine (batched decode)", "paddle_tpu.inference.serving",
     ["ServingEngine"]),
    ("FusedMultiTransformer (serving block)", "paddle_tpu.incubate.nn",
     ["FusedMultiTransformer"]),
    ("TCPStore rendezvous (C++)", "paddle_tpu.distributed.native",
     ["TCPStore", "available"]),
    ("paddle.distribution", "paddle_tpu.distribution",
     ["Normal", "Gamma", "Dirichlet", "MultivariateNormal",
      "TransformedDistribution", "kl_divergence", "register_kl"]),
    ("Pretrained weights (zoo cache + HF interop)", "paddle_tpu.models.pretrained",
     ["load_llama_from_hf", "load_gpt_from_hf", "llama_config_from_hf"]),
    ("nn breadth batch 2 (unpool/3d/losses)", "paddle_tpu.nn",
     ["MaxUnPool2D", "Conv3DTranspose", "HSigmoidLoss", "Fold",
      "PixelUnshuffle", "TripletMarginWithDistanceLoss"]),
    ("paddle.geometric (GNN ops)", "paddle_tpu.geometric",
     ["segment_sum", "send_u_recv", "send_ue_recv", "send_uv"]),
    ("Optimizer breadth (LBFGS tier)", "paddle_tpu.optimizer",
     ["LBFGS", "RAdam", "NAdam", "Rprop", "ASGD"]),
    ("Vision zoo batch 2", "paddle_tpu.vision.models",
     ["AlexNet", "SqueezeNet", "MobileNetV3Small", "ShuffleNetV2",
      "DenseNet", "wide_resnet50_2", "GoogLeNet", "InceptionV3"]),
    ("Compat namespaces", "paddle_tpu",
     ["iinfo", "finfo", "is_tensor", "create_parameter", "flops",
      "LazyGuard"]),
    ("Fused functional shims", "paddle_tpu.incubate.nn.functional",
     ["fused_linear", "fused_dropout_add",
      "fused_bias_dropout_residual_layer_norm"]),
    ("Text datasets (cache-gated)", "paddle_tpu.text",
     ["UCIHousing", "Imdb", "Imikolov"]),
    # -- round 3 additions ---------------------------------------------------
    ("Kernel compile guard (wedge-proof)", "paddle_tpu.utils.guarded_compile",
     ["prove", "kernel_allowed", "CANARIES"]),
    ("Ulysses all-to-all context parallel", "paddle_tpu.distributed.fleet.utils",
     ["ulysses_attention", "UlyssesAttention"]),
    ("Continuous-batching serving", "paddle_tpu.inference",
     ["ContinuousServingEngine"]),
    ("Slot-paged KV cache", "paddle_tpu.models.generation",
     ["SlotPagedKVCache"]),
    ("Donation/aliasing sanitizers", "paddle_tpu.utils.donation",
     ["donated_jit", "assert_no_aliases"]),
    ("Device memory runtime", "paddle_tpu.device.memory",
     ["memory_stats", "live_tensor_report", "memory_summary"]),
    ("Auto-search mesh tuner wiring", "paddle_tpu.distributed.fleet",
     ["_apply_auto_search"]),
    ("Auto-parallel Engine (fit/eval/cost)", "paddle_tpu.distributed.auto_parallel",
     ["Engine"]),
    ("Static inference IO (save/load_inference_model)", "paddle_tpu.static",
     ["save_inference_model", "load_inference_model"]),
    ("GPT pipeline model", "paddle_tpu.models",
     ["GPTForCausalLMPipe"]),
    ("T5 encoder-decoder family", "paddle_tpu.models",
     ["T5ForConditionalGeneration", "T5Config", "t5_tiny"]),
    ("ViT family", "paddle_tpu.vision.models",
     ["VisionTransformer", "vit_base_patch16_224"]),
    ("Sparse op breadth", "paddle_tpu.sparse",
     ["tanh", "transpose", "coalesce", "mask_as", "addmm"]),
    ("Parameter-server mode (ps tables/RPC)", "paddle_tpu.distributed.ps",
     ["SparseTable", "PSServer", "PSClient", "DistributedEmbedding"]),
    ("PIR pass infra (StableHLO rewriter)", "paddle_tpu.static.pir",
     ["ProgramIR", "Pass", "PassRegistry", "PatternRewritePass",
      "MLIRPipelinePass", "optimize_exported"]),
    ("Auto-parallel completion (dist-attr)", "paddle_tpu.distributed.auto_parallel",
     ["Completer", "completion"]),
    ("dy2static control-flow conversion", "paddle_tpu.jit.dy2static",
     ["convert_function", "ConversionUnsupported"]),
    ("1F1B/SPMD pipeline engine", "paddle_tpu.distributed.engine",
     ["pipeline_spmd", "pipeline_spmd_1f1b_bwd", "pipeline_spmd_interleaved",
      "PipelinedModule"]),
    ("Generation (beam search, paged KV)", "paddle_tpu.models.generation",
     ["GenerationMixin", "KVCache", "PagedKVCache", "SlotPagedKVCache"]),
    ("Detection op surface", "paddle_tpu.vision.ops",
     ["matrix_nms", "roi_pool", "roi_align", "deform_conv2d", "nms"]),
    ("Hermitian FFT family", "paddle_tpu.fft",
     ["hfft2", "ihfft2", "hfftn", "ihfftn"]),
    # -- gradient communication layer (EQuARX-style) -------------------------
    ("Bucketed/quantized gradient comm", "paddle_tpu.distributed.comm",
     ["GradientBucketer", "CommStats", "get_comm_stats", "reset_comm_stats",
      "all_reduce_quantized", "reduce_scatter_quantized",
      "quantize_blockwise", "dequantize_blockwise",
      "comm_config_from_strategy"]),
    ("Comm stats via profiler", "paddle_tpu.profiler", ["comm_stats"]),
    # -- unified runtime telemetry (ISSUE 2) ---------------------------------
    ("Telemetry registry + span tracer", "paddle_tpu.profiler.telemetry",
     ["MetricRegistry", "Counter", "Gauge", "Histogram", "SpanTracer",
      "get_registry", "get_tracer", "metrics", "metrics_text",
      "enable_op_telemetry", "disable_op_telemetry"]),
    ("Telemetry facade via profiler", "paddle_tpu.profiler",
     ["metrics", "metrics_text", "get_registry", "get_tracer"]),
    ("Training telemetry callback", "paddle_tpu.callbacks",
     ["TelemetryCallback"]),
    # -- distributed flight recorder (ISSUE 3) -------------------------------
    ("Flight recorder (hang/straggler diagnosis)",
     "paddle_tpu.profiler.flight_recorder",
     ["FlightRecorder", "Watchdog", "get_flight_recorder", "enable",
      "disable", "is_enabled", "record_event", "heartbeat",
      "collective_begin", "collective_end", "register_state_provider",
      "desync_report", "straggler_report", "merge_chrome_traces",
      "merge_rank_snapshots", "publish_snapshot", "gather_metrics"]),
    ("Flight recorder facade via profiler", "paddle_tpu.profiler",
     ["get_flight_recorder", "gather_metrics", "merge_chrome_traces",
      "straggler_report", "desync_report"]),
    ("Elastic KV aggregation stores",
     "paddle_tpu.distributed.fleet.elastic.tcp_kv",
     ["TcpKVStore", "MemKVStore"]),
    # -- serving fast path (ISSUE 4) -----------------------------------------
    ("Prefix-cached shared KV page pool", "paddle_tpu.models.generation",
     ["SlotPagedKVCache", "block_hash_chain"]),
    ("Chunked-prefill continuous scheduler", "paddle_tpu.inference.serving",
     ["ContinuousServingEngine", "DEFAULT_PREFILL_CHUNK_TOKENS"]),
    ("Serving bench (prefix cache on/off)", "bench",
     ["bench_serving", "bench_llama_decode"]),
    # -- overlapped backward + fused step (ISSUE 5) --------------------------
    ("Ready-bucket comm overlap", "paddle_tpu.distributed.comm",
     ["ReadyBucketScheduler", "GradientBucketer"]),
    ("Grad-ready tape hooks", "paddle_tpu.autograd.tape",
     ["register_grad_ready_callback", "unregister_grad_ready_callback"]),
    ("Fused donated optimizer step", "paddle_tpu.optimizer.fused",
     ["FusedStepEngine", "opt_telemetry"]),
    ("Persistent jit compilation cache", "paddle_tpu.jit.api",
     ["enable_persistent_cache"]),
    # -- elastic fault tolerance (ISSUE 6) -----------------------------------
    ("Fault injection harness", "paddle_tpu.distributed.fault",
     ["Fault", "FaultPlan", "install", "clear", "active_plan", "check_step",
      "SimulatedRankKill", "RankFailure", "elastic_telemetry"]),
    ("Structured failure detection (simulator)",
     "paddle_tpu.distributed.simulator",
     ["RankFailure", "SimulatedRankKill", "reset_seqs"]),
    ("Elastic shrink/regrow train loop",
     "paddle_tpu.distributed.fleet.elastic",
     ["ElasticTrainLoop", "ElasticWorld", "WorldChanged", "RankFailure",
      "TrainingSupervisor", "CheckpointManager"]),
    ("Async/sharded checkpoint manager",
     "paddle_tpu.distributed.fleet.elastic.supervisor",
     ["CheckpointManager", "ElasticTrainLoop", "ElasticWorld"]),
    # -- ragged paged attention + token-budget scheduler (ISSUE 7) -----------
    ("Ragged paged attention (mixed prefill+decode kernel)",
     "paddle_tpu.ops.pallas.ragged_paged_attention",
     ["ragged_paged_attention", "ragged_paged_attention_reference"]),
    ("Token-budget continuous batching",
     "paddle_tpu.inference.serving",
     ["ContinuousServingEngine", "DEFAULT_SERVING_TOKEN_BUDGET"]),
    ("Ragged cache step (slot-paged pool)",
     "paddle_tpu.models.generation",
     ["SlotPagedKVCache"]),
    # -- serving fleet (ISSUE 8) ---------------------------------------------
    ("Serving fleet router (affinity/disagg/quotas/health)",
     "paddle_tpu.inference.fleet",
     ["ServingRouter", "Replica", "Rejected", "TenantQuotaManager",
      "ROUTER_POLICIES", "DEFAULT_FLEET_AFFINITY"]),
    ("Fleet KV atomic counters + component-state publish",
     "paddle_tpu.distributed.fleet.elastic.tcp_kv",
     ["MemKVStore", "TcpKVStore"]),
    ("Fleet heartbeat publish path (flight recorder)",
     "paddle_tpu.profiler.flight_recorder",
     ["publish_component_state", "gather_component_states"]),
    # -- per-request tracing + SLO monitor (ISSUE 9) -------------------------
    ("Per-request trace store + SLO monitor",
     "paddle_tpu.profiler.request_trace",
     ["TraceContext", "RequestTraceStore", "SLOMonitor", "start_request",
      "add_span", "add_event", "note_token", "finish_request",
      "request_timeline", "recent_timelines", "timeline_to_chrome",
      "get_slo_monitor", "reset_slo_monitor", "slo_report", "cost_table"]),
    ("Request-trace facade via profiler", "paddle_tpu.profiler",
     ["request_timeline", "slo_report", "cost_table", "get_slo_monitor",
      "timeline_to_chrome", "get_trace_store"]),
    ("Request-flow chrome merge (flow events)",
     "paddle_tpu.profiler.flight_recorder",
     ["merge_chrome_traces"]),
    # -- speculative decoding + int8 KV pages (ISSUE 10) ---------------------
    ("Speculative decoding (drafter tiers + verify path)",
     "paddle_tpu.inference.speculative",
     ["NGramDrafter", "DraftModelDrafter", "make_drafter",
      "DEFAULT_SPEC_K"]),
    ("Slot-paged KV rollback + int8 page codec",
     "paddle_tpu.models.generation",
     ["SlotPagedKVCache", "quantize_kv_rows", "dequantize_kv_rows",
      "kv_page_nbytes"]),
    ("Quantized paged-attention gather tiers",
     "paddle_tpu.ops.pallas.ragged_paged_attention",
     ["ragged_paged_attention"]),
    # -- fleet load observatory (ISSUE 11) -----------------------------------
    ("Metric time-series history (sampler + queries)",
     "paddle_tpu.profiler.timeseries",
     ["MetricsHistory", "get_history", "history", "history_tick",
      "HISTORY_SCHEMA"]),
    ("Alert rules + SLO burn-rate engine",
     "paddle_tpu.profiler.alerts",
     ["AlertEngine", "AlertRule", "ThresholdRule", "BurnRateRule",
      "parse_rules", "get_alert_engine", "active_alerts"]),
    ("Workload replay harness (seeded load generator)",
     "paddle_tpu.inference.fleet.replay",
     ["ReplayHarness", "ReplayReport", "ReplayTrace", "ReplayRequest",
      "make_trace", "load_trace", "time_to_recover", "REPLAY_PRESETS"]),
    # -- training observatory (ISSUE 12) -------------------------------------
    ("Numerics sentinel (per-layer grad stats)",
     "paddle_tpu.profiler.tensor_stats",
     ["NumericsSentinel", "NonFiniteGradError", "get_sentinel", "enable",
      "disable", "attach", "detach", "is_enabled"]),
    ("Step memory timeline + module breakdown",
     "paddle_tpu.profiler.memory",
     ["MemoryTimeline", "get_timeline", "module_breakdown",
      "register_model_breakdown", "phase_sample", "last_breakdown"]),
    ("Step-phase spans (fwd/bwd/comm/opt)",
     "paddle_tpu.profiler.step_phase",
     ["PHASES", "record_phase", "span", "breakdown", "clock",
      "step_begin", "step_end"]),
    # -- determinism observatory (ISSUE 13) ----------------------------------
    ("Determinism ledger (digest sensing + comparator)",
     "paddle_tpu.profiler.ledger",
     ["StepLedger", "DivergenceError", "get_ledger", "enable", "disable",
      "attach", "detach", "is_enabled", "tensor_digest",
      "first_divergence", "record_optimizer_step"]),
    ("Golden ledger export + cross-process publish",
     "paddle_tpu.profiler.ledger",
     ["export_golden", "publish_ledger", "gather_ledgers",
      "compare_store", "LEDGER_SCHEMA", "KV_LEDGER_PREFIX"]),
    ("Token-stream attestation + handoff digests",
     "paddle_tpu.profiler.ledger",
     ["note_stream_token", "stream_digest", "attest_delivery",
      "seal_handoff", "check_handoff", "chain_update", "blob_digest"]),
    # -- self-healing fleet control plane (ISSUE 14) -------------------------
    ("Fleet controller (SLO-driven reconcile loop)",
     "paddle_tpu.inference.fleet.controller",
     ["FleetController", "ControllerAction", "CONTROLLER_ACTIONS"]),
    ("Fleet actuators (scale/flip/shed/supervise surface)",
     "paddle_tpu.inference.fleet",
     ["ServingRouter", "TenantQuotaManager", "REJECTION_REASONS",
      "DEFAULT_FLEET_MAX_ATTEMPTS"]),
    ("Fleet fault directives (kill/stall by routed request)",
     "paddle_tpu.distributed.fault",
     ["FLEET_FAULT_KINDS", "check_fleet_route", "Fault", "FaultPlan"]),
    # -- telemetry plane (ISSUE 15) ------------------------------------------
    ("Per-process telemetry exporter (HTTP endpoints + KV discovery)",
     "paddle_tpu.profiler.exporter",
     ["TelemetryServer", "maybe_start_exporter", "exporter_enabled",
      "ROUTES", "KV_TELEMETRY_PREFIX", "MAX_HISTORY_WINDOW_S",
      "MAX_POST_BYTES"]),
    ("Fleet scrape aggregation (strict parser + merged view)",
     "paddle_tpu.profiler.scrape",
     ["FleetScraper", "parse_metrics_text", "render_metrics_text",
      "merge_instances", "fleet_metrics", "fleet_metrics_text",
      "start_fleet_scraper", "stop_fleet_scraper"]),
    ("Correlated structured event log (JSONL + rotation)",
     "paddle_tpu.profiler.eventlog",
     ["EventLog", "get_event_log", "log_event", "enable", "disable",
      "is_enabled", "EVENTLOG_SCHEMA"]),
    # -- device-tier decode speed (ISSUE 16) ---------------------------------
    ("Q-block ragged attention (fixed-q-block grid)",
     "paddle_tpu.ops.pallas.ragged_paged_attention",
     ["qblock_schedule", "DEFAULT_QBLOCK", "ragged_paged_attention"]),
    ("Int8 weight serving path (quantize + fused forward)",
     "paddle_tpu.quantization",
     ["quantize_linears", "int8_linear"]),
    ("Batched drafting (one padded draft forward per tick)",
     "paddle_tpu.inference.speculative",
     ["DraftModelDrafter", "NGramDrafter"]),
    # -- compile observatory (ISSUE 18) --------------------------------------
    ("Compile observatory (retrace-cause attribution)",
     "paddle_tpu.profiler.compile_observatory",
     ["CompileObservatory", "get_observatory", "observe",
      "declare_family", "register_warmup", "run_warmup",
      "declared_families", "undeclared_families", "snapshot",
      "cost_section", "tensor_arg", "static_arg", "format_signature",
      "SCHEMA"]),
    ("Recompile-storm + family-drift alert rules",
     "paddle_tpu.profiler.alerts",
     ["recompile_storm_rule", "family_drift_rule",
      "DEFAULT_RECOMPILE_BUDGET"]),
    ("Fleet compile scrape (/compile merge)",
     "paddle_tpu.profiler.scrape",
     ["fetch_compile", "merge_compile_snapshots"]),
    # -- tiered KV + long-context sep prefill (ISSUE 19) ---------------------
    ("Host-RAM KV tier (prefix spill pool)",
     "paddle_tpu.models.generation",
     ["HostKVPool", "SlotPagedKVCache"]),
    ("Sep-ring blockwise prefill kernel tier",
     "paddle_tpu.ops.pallas.ring_attention",
     ["blockwise_causal_attention", "ring_partial", "sep_ring_impl",
      "SEP_RING_IMPLS"]),
]

# DistributedStrategy fields exempt from the docs/PERF.md mention rule
# (none today — add a field here only with a reason it cannot matter to
# performance tuning).
STRATEGY_DOC_EXEMPT: set = set()


def check_strategy_docs(verbose=True):
    """Every public ``DistributedStrategy`` field must be mentioned in
    docs/PERF.md — a knob nobody can discover is a knob nobody tunes.
    Returns the list of undocumented fields (empty = pass)."""
    from paddle_tpu.distributed.fleet.distributed_strategy import (
        DistributedStrategy)
    perf_path = os.path.join(os.path.dirname(__file__), "..", "docs",
                             "PERF.md")
    with open(perf_path) as f:
        perf = f.read()
    fields = sorted(k for k in vars(DistributedStrategy())
                    if not k.startswith("_") and k not in STRATEGY_DOC_EXEMPT)
    missing = [f for f in fields if f not in perf]
    if verbose:
        for f in missing:
            print(f"FAIL DistributedStrategy.{f} has no docs/PERF.md mention")
        print(f"{len(fields) - len(missing)}/{len(fields)} strategy fields "
              f"documented")
    return missing


# PADDLE_* env knobs exempt from the docs-mention rule. Add a knob here
# only with a reason it cannot matter to a user tuning or operating the
# system (none today).
ENV_DOC_EXEMPT: set = set()


def check_env_docs(verbose=True):
    """Every ``PADDLE_*`` env knob referenced anywhere in ``paddle_tpu/``
    must be mentioned in at least one ``docs/*.md`` file — an env knob
    nobody can discover is a knob nobody tunes (the PR-5
    DistributedStrategy-field rule, applied to the env surface). Returns
    the list of undocumented knobs (empty = pass)."""
    import re

    root = os.path.join(os.path.dirname(__file__), "..")
    pat = re.compile(r"PADDLE_[A-Z0-9_]*[A-Z0-9]")
    found = set()
    for dirpath, dirnames, filenames in os.walk(
            os.path.join(root, "paddle_tpu")):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in filenames:
            if not name.endswith(".py"):
                continue
            with open(os.path.join(dirpath, name), errors="replace") as f:
                found.update(pat.findall(f.read()))
    docs_text = ""
    docs_dir = os.path.join(root, "docs")
    for name in sorted(os.listdir(docs_dir)):
        if name.endswith(".md"):
            with open(os.path.join(docs_dir, name), errors="replace") as f:
                docs_text += f.read()
    missing = sorted(k for k in found
                     if k not in docs_text and k not in ENV_DOC_EXEMPT)
    if verbose:
        for k in missing:
            print(f"FAIL env knob {k} has no docs/*.md mention")
        print(f"{len(found) - len(missing)}/{len(found)} env knobs "
              f"documented")
    return missing


def check_serving_programs(verbose=True):
    """Compiled-program-count guard for the serving tier: drive a short
    MIXED prefill+decode load through the ragged scheduler and fail if
    any forward ran a shape outside the engine's declared token-bucket
    family — per-request shapes mean unbounded recompiles in production.
    Also proves both token kinds actually flowed through the single
    ragged program family, and (second pass) that speculative-decode
    verify spans (q_len = 1 + k drafted tokens) stay inside the SAME
    declared family — spec decode must not explode the compiled-program
    set — and (third pass) that the fixed-q-block ragged grid
    (``PADDLE_TPU_RAGGED_IMPL=qblock``, the ISSUE-16 default decode
    path) keeps the identical bucket discipline: the q-block schedule
    re-tiles the flat token batch but the engine still pads the token
    dimension to declared buckets. Returns a list of violation
    strings."""
    import threading

    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.inference import ContinuousServingEngine
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny

    paddle.seed(0)
    model = LlamaForCausalLM(llama_tiny(num_hidden_layers=1))
    rng = np.random.RandomState(0)
    # deliberately awkward prompt lengths: none is a bucket size
    prompts = [rng.randint(0, 128, (1, n)).astype(np.int64)
               for n in (13, 3, 21)]

    def drive(eng, reqs, new_tokens=3):
        with eng:
            threads = [threading.Thread(
                target=lambda p=p: eng.generate(p, max_new_tokens=new_tokens,
                                                timeout=300))
                for p in reqs]
            for t in threads:
                t.start()
            for t in threads:
                t.join()

    eng = ContinuousServingEngine(model, max_batch_size=2, max_len=48,
                                  token_budget=16, prefill_chunk_tokens=16)
    drive(eng, prompts)
    declared = eng.declared_token_buckets()
    violations = []
    stray = eng.ragged_buckets_used - declared
    if stray:
        violations.append(
            f"serving ran shapes outside the declared bucket set: "
            f"{sorted(stray)} (declared {sorted(declared)})")
    if not eng.ragged_steps:
        violations.append("mixed load never reached the ragged scheduler")
    if not (eng.ragged_prefill_tokens and eng.ragged_decode_tokens):
        violations.append(
            f"ragged program family missed a token kind: prefill="
            f"{eng.ragged_prefill_tokens} decode={eng.ragged_decode_tokens}")
    # speculative pass: self-draft (acceptance ~1) maximizes verify-span
    # lengths, the worst case for bucket growth
    spec = ContinuousServingEngine(model, max_batch_size=2, max_len=48,
                                   token_budget=16, prefill_chunk_tokens=16,
                                   spec_decode=True, spec_k=3,
                                   draft_model=model)
    drive(spec, prompts[:2], new_tokens=6)
    spec_stray = spec.ragged_buckets_used - spec.declared_token_buckets()
    if spec_stray:
        violations.append(
            f"speculative verify spans ran shapes outside the declared "
            f"bucket set: {sorted(spec_stray)} "
            f"(declared {sorted(spec.declared_token_buckets())})")
    if not spec.spec_drafted_tokens:
        violations.append("speculative pass drafted no tokens")
    # q-block pass: the same mixed load with the fixed-q-block ragged
    # grid forced — the new default decode grid must not grow the
    # compiled-program family
    prev_impl = os.environ.get("PADDLE_TPU_RAGGED_IMPL")
    os.environ["PADDLE_TPU_RAGGED_IMPL"] = "qblock"
    try:
        qb = ContinuousServingEngine(model, max_batch_size=2, max_len=48,
                                     token_budget=16,
                                     prefill_chunk_tokens=16)
        drive(qb, prompts)
    finally:
        if prev_impl is None:
            os.environ.pop("PADDLE_TPU_RAGGED_IMPL", None)
        else:
            os.environ["PADDLE_TPU_RAGGED_IMPL"] = prev_impl
    qb_stray = qb.ragged_buckets_used - qb.declared_token_buckets()
    if qb_stray:
        violations.append(
            f"q-block serving ran shapes outside the declared bucket set: "
            f"{sorted(qb_stray)} (declared "
            f"{sorted(qb.declared_token_buckets())})")
    if not qb.ragged_steps:
        violations.append("q-block pass never reached the ragged scheduler")
    if verbose:
        for v in violations:
            print(f"FAIL {v}")
        print(f"serving programs: {len(eng.ragged_buckets_used)} bucket(s) "
              f"{sorted(eng.ragged_buckets_used)} within declared "
              f"{sorted(declared)}; prefill={eng.ragged_prefill_tokens} "
              f"decode={eng.ragged_decode_tokens} tokens; spec buckets "
              f"{sorted(spec.ragged_buckets_used)} drafted="
              f"{spec.spec_drafted_tokens} accepted="
              f"{spec.spec_accepted_tokens}; qblock buckets "
              f"{sorted(qb.ragged_buckets_used)}")
    return violations


def check_quantized_config(verbose=True):
    """Quantized-config inventory guard (ISSUE 16): every device-tier
    decode-speed knob (int8 weights, q-block ragged grid, batched
    drafting) must be documented in ``docs/*.md`` AND exercised by at
    least one test, and the fully-quantized serving config
    (``weight_dtype="int8"`` + ``kv_dtype="int8"`` under the default
    q-block ragged grid) must be BIT-STABLE: two same-seed runs produce
    byte-identical token streams (sha1 attestation) while staying
    inside the declared bucket family. A quantized path that drifts
    run-to-run is a silent-accuracy incident, not a speed win. Returns
    a list of violation strings."""
    import hashlib
    import threading

    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.inference import ContinuousServingEngine
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny

    root = os.path.join(os.path.dirname(__file__), "..")
    docs_text = ""
    docs_dir = os.path.join(root, "docs")
    for name in sorted(os.listdir(docs_dir)):
        if name.endswith(".md"):
            with open(os.path.join(docs_dir, name), errors="replace") as f:
                docs_text += f.read()
    tests_text = ""
    tests_dir = os.path.join(root, "tests")
    for name in sorted(os.listdir(tests_dir)):
        if name.startswith("test_") and name.endswith(".py"):
            with open(os.path.join(tests_dir, name), errors="replace") as f:
                tests_text += f.read()
    violations = []
    knobs = ["PADDLE_WEIGHT_DTYPE", "PADDLE_TPU_RAGGED_QBLOCK",
             "PADDLE_SPEC_DRAFT_BATCH", "PADDLE_TPU_RAGGED_IMPL",
             "PADDLE_KV_DTYPE"]
    for k in knobs:
        if k not in docs_text:
            violations.append(
                f"quantized-config knob {k} missing from docs/*.md")
        if k not in tests_text:
            violations.append(
                f"quantized-config knob {k} not exercised by any test")
    # impl selector values a user must be able to discover (the quoted
    # form keeps prose mentions of the word "token" from matching)
    for value in ('"qblock"', '"token"'):
        if value.strip('"') not in docs_text:
            violations.append(
                f"ragged impl value {value} missing from docs/*.md")
        if value not in tests_text:
            violations.append(
                f"ragged impl value {value} not exercised by any test")

    def run_once():
        paddle.seed(0)
        model = LlamaForCausalLM(llama_tiny(num_hidden_layers=1))
        rng = np.random.RandomState(0)
        prompts = [rng.randint(0, 128, (1, n)).astype(np.int64)
                   for n in (13, 3, 21)]
        eng = ContinuousServingEngine(
            model, max_batch_size=2, max_len=48, token_budget=16,
            prefill_chunk_tokens=16, weight_dtype="int8", kv_dtype="int8")
        outs = [None] * len(prompts)

        def gen(i, p):
            outs[i] = np.asarray(
                eng.generate(p, max_new_tokens=3, timeout=300).numpy())

        with eng:
            threads = [threading.Thread(target=gen, args=(i, p))
                       for i, p in enumerate(prompts)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        h = hashlib.sha1()
        for o in outs:
            if o is not None:
                h.update(np.ascontiguousarray(o).tobytes())
        return eng, outs, h.hexdigest()

    eng_a, outs_a, dig_a = run_once()
    eng_b, outs_b, dig_b = run_once()
    if not eng_a.quantized_linears:
        violations.append("fully-int8 config quantized no Linear layers")
    stray = eng_a.ragged_buckets_used - eng_a.declared_token_buckets()
    if stray:
        violations.append(
            f"fully-int8 serving ran shapes outside the declared bucket "
            f"set: {sorted(stray)} "
            f"(declared {sorted(eng_a.declared_token_buckets())})")
    for i, (a, b) in enumerate(zip(outs_a, outs_b)):
        if a is None or b is None:
            violations.append(f"fully-int8 request {i} produced no output")
        elif a.shape != b.shape or not (a == b).all():
            violations.append(
                f"fully-int8 config is not bit-stable: request {i} "
                f"diverged between two same-seed runs")
    if dig_a != dig_b:
        violations.append(
            f"fully-int8 token digests differ across same-seed runs: "
            f"{dig_a} vs {dig_b}")
    if verbose:
        for v in violations:
            print(f"FAIL {v}")
        print(f"quantized config: {len(knobs)} knobs checked, "
              f"{eng_a.quantized_linears} Linear(s) quantized, "
              f"token digest {dig_a[:12]} stable across 2 runs")
    return violations


def check_fleet_knobs(verbose=True):
    """Serving-fleet inventory guard: every ``PADDLE_FLEET_*`` env knob
    referenced in ``paddle_tpu/`` must be documented in docs/SERVING.md's
    fleet knob table, and every router policy string
    (``inference.fleet.ROUTER_POLICIES``) must appear in at least one
    test — a routing mode no test exercises is a routing mode that
    silently rots. Returns a list of violation strings."""
    import re

    root = os.path.join(os.path.dirname(__file__), "..")
    pat = re.compile(r"PADDLE_FLEET_[A-Z0-9_]*[A-Z0-9]")
    knobs = set()
    for dirpath, dirnames, filenames in os.walk(
            os.path.join(root, "paddle_tpu")):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in filenames:
            if name.endswith(".py"):
                with open(os.path.join(dirpath, name),
                          errors="replace") as f:
                    knobs.update(pat.findall(f.read()))
    with open(os.path.join(root, "docs", "SERVING.md"),
              errors="replace") as f:
        serving_doc = f.read()
    violations = [f"fleet knob {k} missing from docs/SERVING.md"
                  for k in sorted(knobs) if k not in serving_doc]
    tests_text = ""
    tests_dir = os.path.join(root, "tests")
    for name in sorted(os.listdir(tests_dir)):
        if name.startswith("test_") and name.endswith(".py"):
            with open(os.path.join(tests_dir, name), errors="replace") as f:
                tests_text += f.read()
    from paddle_tpu.inference.fleet import ROUTER_POLICIES
    for policy in ROUTER_POLICIES:
        if f'"{policy}"' not in tests_text:
            violations.append(
                f"router policy {policy!r} not exercised by any test")
        if policy not in serving_doc:
            violations.append(
                f"router policy {policy!r} missing from docs/SERVING.md")
    if verbose:
        for v in violations:
            print(f"FAIL {v}")
        print(f"fleet knobs: {len(knobs)} found, "
              f"{len(ROUTER_POLICIES)} policies checked")
    return violations


def check_observability_catalog(verbose=True):
    """Request-trace/SLO/spec-decode inventory guard: every
    ``paddle_request_*`` / ``paddle_slo_*`` / ``paddle_spec_*`` metric
    name and every ``PADDLE_SLO_*`` / ``PADDLE_REQUEST_TRACE*`` /
    ``PADDLE_SPEC_*`` / ``PADDLE_KV_*`` env knob referenced in
    ``paddle_tpu/`` must be cataloged in docs/OBSERVABILITY.md (knobs
    may live in any docs/*.md via check_env_docs, but the metric names
    must be in the catalog) — these layers exist so operators can SEE;
    an uncataloged signal defeats it. Returns a list of violation
    strings."""
    import re

    root = os.path.join(os.path.dirname(__file__), "..")
    metric_pat = re.compile(
        r"paddle_(?:request|slo|spec)_[a-z0-9_]*[a-z0-9]")
    knob_pat = re.compile(
        r"PADDLE_(?:SLO|REQUEST_TRACE)[A-Z0-9_]*")
    metrics, knobs = set(), set()
    for dirpath, dirnames, filenames in os.walk(
            os.path.join(root, "paddle_tpu")):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in filenames:
            if name.endswith(".py"):
                with open(os.path.join(dirpath, name),
                          errors="replace") as f:
                    text = f.read()
                metrics.update(metric_pat.findall(text))
                knobs.update(knob_pat.findall(text))
    with open(os.path.join(root, "docs", "OBSERVABILITY.md"),
              errors="replace") as f:
        doc = f.read()
    violations = [f"request/SLO metric {m} missing from "
                  f"docs/OBSERVABILITY.md"
                  for m in sorted(metrics) if m not in doc]
    violations += [f"request-trace knob {k} missing from "
                   f"docs/OBSERVABILITY.md"
                   for k in sorted(knobs) if k not in doc]
    if verbose:
        for v in violations:
            print(f"FAIL {v}")
        print(f"observability catalog: {len(metrics)} request/SLO "
              f"metrics, {len(knobs)} knobs checked")
    return violations


def check_alert_catalog(verbose=True):
    """Fleet-observatory inventory guard: every ``PADDLE_HISTORY_*`` /
    ``PADDLE_ALERT_*`` / ``PADDLE_REPLAY_*`` / ``PADDLE_TELEMETRY_*``
    env knob and every ``paddle_history_*`` / ``paddle_alert*_*``
    metric referenced in ``paddle_tpu/`` must be (a) cataloged in
    docs/OBSERVABILITY.md and (b) exercised by at least one test —
    an alerting signal nobody documents or tests is a pager that lies.
    Every replay preset string must appear in a test too (same rule as
    the router policies). Returns a list of violation strings."""
    import re

    root = os.path.join(os.path.dirname(__file__), "..")
    knob_pat = re.compile(
        r"PADDLE_(?:HISTORY|ALERT|REPLAY|TELEMETRY)[A-Z0-9_]*")
    metric_pat = re.compile(r"paddle_(?:history|alerts?)_[a-z0-9_]*[a-z0-9]")
    knobs, metrics = set(), set()
    for dirpath, dirnames, filenames in os.walk(
            os.path.join(root, "paddle_tpu")):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in filenames:
            if name.endswith(".py"):
                with open(os.path.join(dirpath, name),
                          errors="replace") as f:
                    text = f.read()
                knobs.update(knob_pat.findall(text))
                metrics.update(metric_pat.findall(text))
    with open(os.path.join(root, "docs", "OBSERVABILITY.md"),
              errors="replace") as f:
        doc = f.read()
    tests_text = ""
    tests_dir = os.path.join(root, "tests")
    for name in sorted(os.listdir(tests_dir)):
        if name.startswith("test_") and name.endswith(".py"):
            with open(os.path.join(tests_dir, name), errors="replace") as f:
                tests_text += f.read()
    violations = []
    for k in sorted(knobs):
        if k not in doc:
            violations.append(
                f"observatory knob {k} missing from docs/OBSERVABILITY.md")
        if k not in tests_text:
            violations.append(
                f"observatory knob {k} not exercised by any test")
    for m in sorted(metrics):
        if m not in doc:
            violations.append(
                f"observatory metric {m} missing from "
                f"docs/OBSERVABILITY.md")
        if m not in tests_text:
            violations.append(
                f"observatory metric {m} not exercised by any test")
    from paddle_tpu.inference.fleet import REPLAY_PRESETS
    for preset in REPLAY_PRESETS:
        if f'"{preset}"' not in tests_text:
            violations.append(
                f"replay preset {preset!r} not exercised by any test")
        if preset not in doc:
            violations.append(
                f"replay preset {preset!r} missing from "
                f"docs/OBSERVABILITY.md")
    if verbose:
        for v in violations:
            print(f"FAIL {v}")
        print(f"alert catalog: {len(knobs)} knobs, {len(metrics)} "
              f"metrics, {len(REPLAY_PRESETS)} presets checked")
    return violations


def check_training_observability(verbose=True):
    """Training-observatory inventory guard: every ``PADDLE_NUMERICS_*``
    / ``PADDLE_MEMORY_*`` / ``PADDLE_STEP_PHASE*`` env knob and every
    ``paddle_numerics_*`` / ``paddle_memory_*`` / ``paddle_step_phase_*``
    metric referenced in ``paddle_tpu/`` must be (a) cataloged in
    docs/OBSERVABILITY.md and (b) exercised by at least one test — the
    same rule the fleet observatory lives under (check_alert_catalog):
    a numerics guard nobody documents or tests is a guard that lies.
    Returns a list of violation strings."""
    import re

    root = os.path.join(os.path.dirname(__file__), "..")
    knob_pat = re.compile(
        r"PADDLE_(?:NUMERICS|MEMORY|STEP_PHASE)[A-Z0-9_]*")
    metric_pat = re.compile(
        r"paddle_(?:numerics|memory|step_phase)_[a-z0-9_]*[a-z0-9]")
    knobs, metrics = set(), set()
    for dirpath, dirnames, filenames in os.walk(
            os.path.join(root, "paddle_tpu")):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in filenames:
            if name.endswith(".py"):
                with open(os.path.join(dirpath, name),
                          errors="replace") as f:
                    text = f.read()
                knobs.update(knob_pat.findall(text))
                metrics.update(metric_pat.findall(text))
    with open(os.path.join(root, "docs", "OBSERVABILITY.md"),
              errors="replace") as f:
        doc = f.read()
    tests_text = ""
    tests_dir = os.path.join(root, "tests")
    for name in sorted(os.listdir(tests_dir)):
        if name.startswith("test_") and name.endswith(".py"):
            with open(os.path.join(tests_dir, name), errors="replace") as f:
                tests_text += f.read()
    violations = []
    for k in sorted(knobs):
        if k not in doc:
            violations.append(
                f"training-observability knob {k} missing from "
                f"docs/OBSERVABILITY.md")
        if k not in tests_text:
            violations.append(
                f"training-observability knob {k} not exercised by any "
                f"test")
    for m in sorted(metrics):
        if m not in doc:
            violations.append(
                f"training-observability metric {m} missing from "
                f"docs/OBSERVABILITY.md")
        if m not in tests_text:
            violations.append(
                f"training-observability metric {m} not exercised by "
                f"any test")
    if verbose:
        for v in violations:
            print(f"FAIL {v}")
        print(f"training observability: {len(knobs)} knobs, "
              f"{len(metrics)} metrics checked")
    return violations


def check_ledger_catalog(verbose=True):
    """Determinism-observatory inventory guard: every ``PADDLE_LEDGER*``
    env knob and every ``paddle_ledger_*`` metric referenced in
    ``paddle_tpu/`` must be (a) cataloged in docs/OBSERVABILITY.md and
    (b) exercised by at least one test — same rule as the fleet and
    training observatories: a divergence sensor nobody documents or
    tests is a sensor that lies. Returns a list of violation strings."""
    import re

    root = os.path.join(os.path.dirname(__file__), "..")
    knob_pat = re.compile(r"PADDLE_LEDGER[A-Z0-9_]*")
    metric_pat = re.compile(r"paddle_ledger_[a-z0-9_]*[a-z0-9]")
    knobs, metrics = set(), set()
    for dirpath, dirnames, filenames in os.walk(
            os.path.join(root, "paddle_tpu")):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in filenames:
            if name.endswith(".py"):
                with open(os.path.join(dirpath, name),
                          errors="replace") as f:
                    text = f.read()
                knobs.update(knob_pat.findall(text))
                metrics.update(metric_pat.findall(text))
    with open(os.path.join(root, "docs", "OBSERVABILITY.md"),
              errors="replace") as f:
        doc = f.read()
    tests_text = ""
    tests_dir = os.path.join(root, "tests")
    for name in sorted(os.listdir(tests_dir)):
        if name.startswith("test_") and name.endswith(".py"):
            with open(os.path.join(tests_dir, name), errors="replace") as f:
                tests_text += f.read()
    violations = []
    for k in sorted(knobs):
        if k not in doc:
            violations.append(
                f"ledger knob {k} missing from docs/OBSERVABILITY.md")
        if k not in tests_text:
            violations.append(
                f"ledger knob {k} not exercised by any test")
    for m in sorted(metrics):
        if m not in doc:
            violations.append(
                f"ledger metric {m} missing from docs/OBSERVABILITY.md")
        if m not in tests_text:
            violations.append(
                f"ledger metric {m} not exercised by any test")
    if verbose:
        for v in violations:
            print(f"FAIL {v}")
        print(f"ledger catalog: {len(knobs)} knobs, {len(metrics)} "
              f"metrics checked")
    return violations


def check_controller_catalog(verbose=True):
    """Fleet-control-plane inventory guard (ISSUE 14): every
    ``PADDLE_CONTROLLER_*`` env knob and ``paddle_controller_*`` metric
    referenced in ``paddle_tpu/`` must be documented (knobs in
    docs/SERVING.md's controller table, metrics in
    docs/OBSERVABILITY.md) AND exercised by at least one test; every
    controller action string (``CONTROLLER_ACTIONS``), fleet fault
    directive (``kill:replica`` / ``stall:replica``) and structured
    rejection reason (``REJECTION_REASONS``) must be documented and
    tested too — a self-healing loop nobody can audit is a loop nobody
    will trust. Returns a list of violation strings."""
    import re

    root = os.path.join(os.path.dirname(__file__), "..")
    knob_pat = re.compile(r"PADDLE_CONTROLLER_[A-Z0-9_]*[A-Z0-9]")
    metric_pat = re.compile(r"paddle_controller_[a-z0-9_]*[a-z0-9]")
    knobs, metrics = set(), set()
    for dirpath, dirnames, filenames in os.walk(
            os.path.join(root, "paddle_tpu")):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in filenames:
            if name.endswith(".py"):
                with open(os.path.join(dirpath, name),
                          errors="replace") as f:
                    text = f.read()
                knobs.update(knob_pat.findall(text))
                metrics.update(metric_pat.findall(text))
    with open(os.path.join(root, "docs", "SERVING.md"),
              errors="replace") as f:
        serving_doc = f.read()
    with open(os.path.join(root, "docs", "OBSERVABILITY.md"),
              errors="replace") as f:
        obs_doc = f.read()
    with open(os.path.join(root, "docs", "ROBUSTNESS.md"),
              errors="replace") as f:
        robust_doc = f.read()
    tests_text = ""
    tests_dir = os.path.join(root, "tests")
    for name in sorted(os.listdir(tests_dir)):
        if name.startswith("test_") and name.endswith(".py"):
            with open(os.path.join(tests_dir, name), errors="replace") as f:
                tests_text += f.read()
    violations = []
    for k in sorted(knobs):
        if k not in serving_doc:
            violations.append(
                f"controller knob {k} missing from docs/SERVING.md")
        if k not in tests_text:
            violations.append(
                f"controller knob {k} not exercised by any test")
    for m in sorted(metrics):
        if m not in obs_doc:
            violations.append(
                f"controller metric {m} missing from "
                f"docs/OBSERVABILITY.md")
        if m not in tests_text:
            violations.append(
                f"controller metric {m} not exercised by any test")
    from paddle_tpu.distributed.fault import FLEET_FAULT_KINDS
    from paddle_tpu.inference.fleet import (CONTROLLER_ACTIONS,
                                            REJECTION_REASONS)
    for action in CONTROLLER_ACTIONS:
        if f'"{action}"' not in tests_text:
            violations.append(
                f"controller action {action!r} not exercised by any test")
        if f"`{action}`" not in serving_doc:
            violations.append(
                f"controller action {action!r} missing from "
                f"docs/SERVING.md")
    for kind in FLEET_FAULT_KINDS:
        directive = f"{kind}:replica"
        if directive not in tests_text:
            violations.append(
                f"fleet fault directive {directive!r} not exercised by "
                f"any test")
        if directive not in robust_doc:
            violations.append(
                f"fleet fault directive {directive!r} missing from "
                f"docs/ROBUSTNESS.md")
    for reason in REJECTION_REASONS:
        if f'"{reason}"' not in tests_text:
            violations.append(
                f"rejection reason {reason!r} not exercised by any test")
        if f"`{reason}`" not in serving_doc:
            violations.append(
                f"rejection reason {reason!r} missing from "
                f"docs/SERVING.md")
    if verbose:
        for v in violations:
            print(f"FAIL {v}")
        print(f"controller catalog: {len(knobs)} knobs, {len(metrics)} "
              f"metrics, {len(CONTROLLER_ACTIONS)} actions, "
              f"{len(FLEET_FAULT_KINDS)} fleet fault kinds, "
              f"{len(REJECTION_REASONS)} rejection reasons checked")
    return violations


def check_telemetry_plane(verbose=True):
    """Telemetry-plane inventory guard (ISSUE 15): every
    ``PADDLE_TELEMETRY_*`` / ``PADDLE_EVENTLOG*`` env knob, every
    ``paddle_telemetry_*`` / ``paddle_eventlog_*`` metric referenced in
    ``paddle_tpu/`` AND every exporter HTTP route
    (``profiler.exporter.ROUTES``) must be cataloged in
    docs/OBSERVABILITY.md and exercised by at least one test — a remote
    diagnosis surface nobody documents or tests is a dashboard that
    404s during the incident. Returns a list of violation strings."""
    import re

    root = os.path.join(os.path.dirname(__file__), "..")
    knob_pat = re.compile(r"PADDLE_(?:TELEMETRY|EVENTLOG)[A-Z0-9_]*")
    metric_pat = re.compile(
        r"paddle_(?:telemetry|eventlog)_[a-z0-9_]*[a-z0-9]")
    knobs, metrics = set(), set()
    for dirpath, dirnames, filenames in os.walk(
            os.path.join(root, "paddle_tpu")):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in filenames:
            if name.endswith(".py"):
                with open(os.path.join(dirpath, name),
                          errors="replace") as f:
                    text = f.read()
                knobs.update(knob_pat.findall(text))
                metrics.update(metric_pat.findall(text))
    with open(os.path.join(root, "docs", "OBSERVABILITY.md"),
              errors="replace") as f:
        doc = f.read()
    tests_text = ""
    tests_dir = os.path.join(root, "tests")
    for name in sorted(os.listdir(tests_dir)):
        if name.startswith("test_") and name.endswith(".py"):
            with open(os.path.join(tests_dir, name), errors="replace") as f:
                tests_text += f.read()
    violations = []
    for k in sorted(knobs):
        if k not in doc:
            violations.append(
                f"telemetry-plane knob {k} missing from "
                f"docs/OBSERVABILITY.md")
        if k not in tests_text:
            violations.append(
                f"telemetry-plane knob {k} not exercised by any test")
    for m in sorted(metrics):
        if m not in doc:
            violations.append(
                f"telemetry-plane metric {m} missing from "
                f"docs/OBSERVABILITY.md")
        if m not in tests_text:
            violations.append(
                f"telemetry-plane metric {m} not exercised by any test")
    from paddle_tpu.profiler.exporter import ROUTES
    for route in ROUTES:
        # backtick-prefix match: `/timeline/<trace_id>` documents the
        # /timeline route
        if f"`{route}" not in doc:
            violations.append(
                f"exporter route {route!r} missing from "
                f"docs/OBSERVABILITY.md")
        if route not in tests_text:
            violations.append(
                f"exporter route {route!r} not exercised by any test")
    if verbose:
        for v in violations:
            print(f"FAIL {v}")
        print(f"telemetry plane: {len(knobs)} knobs, {len(metrics)} "
              f"metrics, {len(ROUTES)} routes checked")
    return violations


def check_kv_tier(verbose=True):
    """Tiered-KV / long-context inventory guard (ISSUE 19): every
    ``PADDLE_KV_HOST_*`` and ``PADDLE_SEP_*`` env knob referenced in
    ``paddle_tpu/`` must be documented in docs/SERVING.md's tiered-KV
    knob table AND exercised by at least one test, and every
    ``paddle_kv_*`` metric (plus the tier-labelled prefix-eviction
    counter) must be cataloged in docs/OBSERVABILITY.md AND exercised
    by a test — eviction was silent before this layer existed; an
    undocumented spill knob or counter would make it silent again.
    Returns a list of violation strings."""
    import re

    root = os.path.join(os.path.dirname(__file__), "..")
    knob_pat = re.compile(r"PADDLE_(?:KV_HOST|SEP)_[A-Z0-9_]*")
    metric_pat = re.compile(r"paddle_kv_[a-z0-9_]*[a-z0-9]")
    knobs, metrics = set(), set()
    for dirpath, dirnames, filenames in os.walk(
            os.path.join(root, "paddle_tpu")):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in filenames:
            if name.endswith(".py"):
                with open(os.path.join(dirpath, name),
                          errors="replace") as f:
                    text = f.read()
                knobs.update(knob_pat.findall(text))
                metrics.update(metric_pat.findall(text))
    metrics.add("paddle_serving_prefix_evictions_total")
    with open(os.path.join(root, "docs", "SERVING.md"),
              errors="replace") as f:
        serving_doc = f.read()
    with open(os.path.join(root, "docs", "OBSERVABILITY.md"),
              errors="replace") as f:
        obs_doc = f.read()
    tests_text = ""
    tests_dir = os.path.join(root, "tests")
    for name in sorted(os.listdir(tests_dir)):
        if name.startswith("test_") and name.endswith(".py"):
            with open(os.path.join(tests_dir, name),
                      errors="replace") as f:
                tests_text += f.read()
    violations = []
    for k in sorted(knobs):
        if k not in serving_doc:
            violations.append(
                f"kv-tier knob {k} missing from docs/SERVING.md")
        if k not in tests_text:
            violations.append(
                f"kv-tier knob {k} not exercised by any test")
    for m in sorted(metrics):
        if m not in obs_doc:
            violations.append(
                f"kv-tier metric {m} missing from docs/OBSERVABILITY.md")
        if m not in tests_text:
            violations.append(
                f"kv-tier metric {m} not exercised by any test")
    if verbose:
        for v in violations:
            print(f"FAIL {v}")
        print(f"kv tier: {len(knobs)} knobs, {len(metrics)} metrics "
              f"checked")
    return violations


def check_compile_observatory(verbose=True):
    """Compile-observatory inventory guard (ISSUE 18). Two halves:

    Catalog: every ``PADDLE_COMPILE*`` env knob and every
    ``paddle_compile_*`` metric referenced in ``paddle_tpu/`` must be
    documented in docs/OBSERVABILITY.md AND exercised by at least one
    test — the same contract every other observability layer lives
    under.

    Runtime drift: a short mixed prefill+decode replay through a warmed
    engine must (a) observe ONLY program families that were declared in
    the inventory (a serve-time family the fleet doesn't account for is
    drift), (b) find a registered warmup entry for every declared
    family, and (c) record ZERO trace-cache misses after
    ``warmup_programs()`` — steady-state serving must never recompile.
    Returns a list of violation strings."""
    import re
    import threading

    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.inference import ContinuousServingEngine
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    from paddle_tpu.profiler import compile_observatory as co

    root = os.path.join(os.path.dirname(__file__), "..")
    knob_pat = re.compile(r"PADDLE_COMPILE[A-Z0-9_]*")
    metric_pat = re.compile(r"paddle_compile_[a-z0-9_]*[a-z0-9]")
    knobs, metrics = set(), set()
    for dirpath, dirnames, filenames in os.walk(
            os.path.join(root, "paddle_tpu")):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in filenames:
            if name.endswith(".py"):
                with open(os.path.join(dirpath, name),
                          errors="replace") as f:
                    text = f.read()
                knobs.update(knob_pat.findall(text))
                metrics.update(metric_pat.findall(text))
    # the snapshot schema token ("paddle_compile_observatory/1") matches
    # the metric pattern but is not a metric family
    metrics.discard("paddle_compile_observatory")
    with open(os.path.join(root, "docs", "OBSERVABILITY.md"),
              errors="replace") as f:
        doc = f.read()
    tests_text = ""
    tests_dir = os.path.join(root, "tests")
    for name in sorted(os.listdir(tests_dir)):
        if name.startswith("test_") and name.endswith(".py"):
            with open(os.path.join(tests_dir, name), errors="replace") as f:
                tests_text += f.read()
    violations = []
    for k in sorted(knobs):
        if k not in doc:
            violations.append(
                f"compile-observatory knob {k} missing from "
                f"docs/OBSERVABILITY.md")
        if k not in tests_text:
            violations.append(
                f"compile-observatory knob {k} not exercised by any test")
    for m in sorted(metrics):
        if m not in doc:
            violations.append(
                f"compile-observatory metric {m} missing from "
                f"docs/OBSERVABILITY.md")
        if m not in tests_text:
            violations.append(
                f"compile-observatory metric {m} not exercised by any "
                f"test")
    # runtime drift pass: warmed engine + mixed replay, observed ⊆
    # declared, warmup entry per declared family, zero post-warmup misses
    co.reset()
    co.enable()
    try:
        paddle.seed(0)
        model = LlamaForCausalLM(llama_tiny(num_hidden_layers=1))
        rng = np.random.RandomState(0)
        prompts = [rng.randint(0, 128, (1, n)).astype(np.int64)
                   for n in (13, 3, 21)]
        eng = ContinuousServingEngine(model, max_batch_size=2, max_len=48,
                                      token_budget=16,
                                      prefill_chunk_tokens=16)
        with eng:
            eng.warmup_programs()
            base = co.snapshot()["totals"]["misses"]
            threads = [threading.Thread(
                target=lambda p=p: eng.generate(p, max_new_tokens=3,
                                                timeout=300))
                for p in prompts]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        snap = co.snapshot()
        if snap["undeclared"]:
            violations.append(
                f"runtime-observed program families never declared: "
                f"{snap['undeclared']} (declared "
                f"{sorted(co.declared_families())})")
        missing_warmup = sorted(set(co.declared_families())
                                - set(co.warmup_entries()))
        if missing_warmup:
            violations.append(
                f"declared families without a registered warmup entry: "
                f"{missing_warmup}")
        post = snap["totals"]["misses"] - base
        if post:
            causes = [c["cause"]
                      for f in snap["families"].values()
                      for c in f.get("last_causes", [])]
            violations.append(
                f"{post} post-warmup trace-cache miss(es) in the mixed "
                f"replay (steady state must be 0); causes: "
                f"{causes[-int(post):]}")
        if verbose:
            for v in violations:
                print(f"FAIL {v}")
            print(f"compile observatory: {len(knobs)} knobs, "
                  f"{len(metrics)} metrics checked; families "
                  f"{sorted(snap['families'])} warmed, "
                  f"{post} post-warmup misses")
    finally:
        co.reset()
    return violations


def check(verbose=True):
    failures = []
    for item, mod_path, symbols in INVENTORY:
        try:
            mod = importlib.import_module(mod_path)
        except Exception as e:
            failures.append((item, mod_path, f"import failed: {e}"))
            continue
        missing = [s for s in symbols if not hasattr(mod, s)]
        if missing:
            failures.append((item, mod_path, f"missing {missing}"))
        elif verbose:
            print(f"  OK {item:<42} {mod_path}")
    if failures:
        for item, mod, why in failures:
            print(f"FAIL {item:<42} {mod}: {why}")
    if verbose:
        print(f"{len(INVENTORY) - len(failures)}/{len(INVENTORY)} "
              f"inventory items resolved")
    return failures


if __name__ == "__main__":
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.exit(1 if (check() or check_strategy_docs() or check_env_docs()
                   or check_fleet_knobs() or check_observability_catalog()
                   or check_alert_catalog() or check_training_observability()
                   or check_ledger_catalog() or check_controller_catalog()
                   or check_telemetry_plane() or check_serving_programs()
                   or check_quantized_config()
                   or check_compile_observatory() or check_kv_tier())
             else 0)
