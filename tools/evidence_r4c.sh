#!/bin/bash
# Round-4 evidence pack, take 3 (fresh container 2026-07-31; pool healthy,
# probe + real matmul verified on-chip at 03:17Z).
# Take-1 history: resnet landed (117k img/s) then the flash-attention
# Mosaic canary wedged the remote pool server-side. Take-2 never got a
# healthy pool again that session. This runner is ZERO-Mosaic end to end
# (BENCH_PROVE=0 everywhere; decode pinned to the pure-XLA paged tier) and
# writes every number incrementally so a mid-pack wedge loses nothing.
set -u
cd /root/repo
PACK=/root/repo/BENCH_R4_PACK.jsonl
SWEEP=/root/repo/BENCH_SWEEP_R4.jsonl
LOG=/tmp/evidence_r4c.log
: > "$PACK"; : > "$SWEEP"
echo "[r4c] start $(date -u +%H:%M:%SZ)" >> "$LOG"

run_one() {  # run_one <outfile> <label> <env...>
  local out=$1 label=$2; shift 2
  local line
  line=$(env "$@" BENCH_PROVE=0 BENCH_PROBE_TIMEOUT=150 timeout 2400 python bench.py 2>>"$LOG" | tail -1)
  if ! printf '%s' "$line" | python -c 'import json,sys; json.loads(sys.stdin.read())' 2>/dev/null; then
    line='{"error": "bench produced no parseable JSON (timeout/kill?)"}'
  fi
  printf '{"label": "%s", "result": %s}\n' "$label" "$line" >> "$out"
  echo "[r4c] $label -> $line" >> "$LOG"
}

# Phase A: headline benches, safest first.
run_one "$PACK" resnet               BENCH_MODEL=resnet
run_one "$PACK" llama_xla_attn       BENCH_MODEL=llama
run_one "$PACK" bert                 BENCH_MODEL=bert
run_one "$PACK" llama_decode_xla     BENCH_MODEL=llama_decode PADDLE_TPU_PAGED_IMPL=xla
run_one "$PACK" data_goodput         BENCH_MODEL=data
run_one "$PACK" resnet_loader        BENCH_MODEL=resnet BENCH_DATA=loader
run_one "$PACK" dispatch             BENCH_MODEL=dispatch

# Phase B: MFU sweep on the XLA-attention path (VERDICT r3 item 2).
for cfg in \
  "BENCH_PRESET=1b BENCH_BATCH=4 BENCH_SEQ=2048 BENCH_REMAT=1" \
  "BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=1" \
  "BENCH_PRESET=1b BENCH_BATCH=16 BENCH_SEQ=2048 BENCH_REMAT=1" \
  "BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=4096 BENCH_REMAT=1" \
  "BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=0" \
  "BENCH_PRESET=1b BENCH_BATCH=16 BENCH_SEQ=1024 BENCH_REMAT=0" \
  "BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=1 PADDLE_TPU_XFA_BLOCK_Q=256" \
  "BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=1 PADDLE_TPU_XFA_BLOCK_Q=512 PADDLE_TPU_XFA_BLOCK_K=512" \
  "BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=1 PADDLE_TPU_XFA_BLOCK_Q=1024 PADDLE_TPU_XFA_BLOCK_K=2048" \
  "BENCH_BATCH=16 BENCH_SEQ=2048" \
  "BENCH_BATCH=32 BENCH_SEQ=1024" ; do
  line=$(env $cfg BENCH_MODEL=llama BENCH_PROVE=0 BENCH_PROBE_TIMEOUT=150 \
         timeout 2400 python bench.py 2>>"$LOG" | tail -1)
  if ! printf '%s' "$line" | python -c 'import json,sys; json.loads(sys.stdin.read())' 2>/dev/null; then
    line='{"error": "bench run produced no parseable JSON (timeout/kill?)"}'
  fi
  echo "{\"config\": \"$cfg xla-attn\", \"result\": $line}" >> "$SWEEP"
  echo "[r4c] sweep $cfg -> $line" >> "$LOG"
done

python - <<'EOF'
import json
results = []
with open("/root/repo/BENCH_R4_PACK.jsonl") as f:
    for line in f:
        line = line.strip()
        if line:
            results.append(json.loads(line))
with open("/root/repo/BENCH_TPU_SESSION_R4.json", "w") as f:
    json.dump({"session": "round4", "results": results}, f, indent=1)
print("assembled", len(results), "results")
EOF
echo "[r4c] done $(date -u +%H:%M:%SZ)" >> "$LOG"
