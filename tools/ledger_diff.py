"""Golden-ledger comparator: diff two determinism-ledger JSONL exports
and report the FIRST divergent step/tensor/request.

``paddle_tpu.profiler.ledger.export_golden()`` writes a deterministic
(timestamp-free, sorted) JSONL file of content digests: per-(rank, step)
parameter/gradient digests, per-(trace, attempt) delivered-token-stream
digests, and KV-handoff blob digests. Two bit-identical runs produce
byte-identical ledgers — so CI can run a seeded job, export, and diff
against a committed golden: the first line of this tool's output names
the exact step and tensor (or request) where a run went off the rails,
which is precisely the bisect anchor the "silent divergence" runbook
(docs/RUNBOOK.md) starts from.

Usage::

    python tools/ledger_diff.py GOLDEN.jsonl CANDIDATE.jsonl
    python tools/ledger_diff.py --json A.jsonl B.jsonl

Exit codes: 0 ledgers identical, 1 divergence(s), 2 usage/input error.
Same import discipline as ``bench_compare.py``: stdlib-only, no
jax/numpy — this runs on a laptop against ledgers scp'd off the fleet.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

LEDGER_SCHEMA = "paddle_ledger/1"


def load_ledger(path: str) -> dict:
    """Parse one JSONL ledger into ``{"steps": {(rank, step): row},
    "streams": {(trace, attempt): row}, "handoffs": [...]}``."""
    steps, streams, handoffs = {}, {}, []
    schema = None
    with open(path) as f:
        for ln, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{ln}: not JSON ({e})") from e
            kind = row.get("kind")
            if kind == "meta":
                schema = row.get("schema")
            elif kind == "step":
                steps[(int(row["rank"]), int(row["step"]))] = row
            elif kind == "stream":
                streams[(str(row["trace"]), int(row["attempt"]))] = row
            elif kind == "handoff":
                handoffs.append(row)
            else:
                raise ValueError(f"{path}:{ln}: unknown row kind {kind!r}")
    if schema is None:
        raise ValueError(f"{path}: no meta line (is this a ledger "
                         f"export from export_golden()?)")
    if schema != LEDGER_SCHEMA:
        raise ValueError(f"{path}: schema {schema!r}, expected "
                         f"{LEDGER_SCHEMA!r}")
    return {"steps": steps, "streams": streams, "handoffs": handoffs}


def _name_of(row, key):
    """Human parameter name for a positional entry key, if recorded."""
    kind, _, pkey = key.partition(":")
    name = (row.get("names") or {}).get(pkey)
    return f"{kind}:{name}" if name else key


def diff_ledgers(a: dict, b: dict) -> list:
    """Ordered divergence records, first (= lowest step, then rank, then
    canonical tensor order) first. Each record:
    ``{"kind", "step"/"trace", ..., "tensor", "a", "b"}``; a row present
    on one side only reports digests of ``None`` on the other."""
    out = []
    # -- training step rows, in (step, rank) order ---------------------------
    for (rank, step) in sorted(set(a["steps"]) | set(b["steps"]),
                               key=lambda k: (k[1], k[0])):
        ra, rb = a["steps"].get((rank, step)), b["steps"].get((rank, step))
        if ra is None or rb is None:
            out.append({"kind": "step", "step": step, "rank": rank,
                        "tensor": "(entire row)",
                        "a": "present" if ra else None,
                        "b": "present" if rb else None})
            continue
        ea, eb = ra.get("entries", {}), rb.get("entries", {})
        for name in sorted(set(ea) | set(eb)):
            if ea.get(name) != eb.get(name):
                out.append({"kind": "step", "step": step, "rank": rank,
                            "tensor": _name_of(ra, name), "entry": name,
                            "a": ea.get(name), "b": eb.get(name)})
                break          # first divergent tensor of this row
    # -- delivered-token streams, in (trace, attempt) order ------------------
    for key in sorted(set(a["streams"]) | set(b["streams"])):
        sa, sb = a["streams"].get(key), b["streams"].get(key)
        da = (sa or {}).get("digest"), (sa or {}).get("count")
        db = (sb or {}).get("digest"), (sb or {}).get("count")
        if da != db:
            out.append({"kind": "stream", "trace": key[0],
                        "attempt": key[1],
                        "tensor": f"tokens:{key[0]}",
                        "a": da[0], "b": db[0],
                        "count_a": da[1], "count_b": db[1]})
    # -- handoffs, positional ------------------------------------------------
    for i in range(max(len(a["handoffs"]), len(b["handoffs"]))):
        ha = a["handoffs"][i] if i < len(a["handoffs"]) else None
        hb = b["handoffs"][i] if i < len(b["handoffs"]) else None
        if (ha or {}).get("digest") != (hb or {}).get("digest"):
            out.append({"kind": "handoff", "index": i,
                        "tensor": f"handoff[{i}]",
                        "a": (ha or {}).get("digest"),
                        "b": (hb or {}).get("digest")})
    return out


def render_text(divs, a_path, b_path) -> str:
    lines = [f"ledger diff: {os.path.basename(a_path)} -> "
             f"{os.path.basename(b_path)}"]
    if not divs:
        lines.append("ledgers identical")
        return "\n".join(lines) + "\n"
    first = divs[0]
    if first["kind"] == "step":
        lines.append(f"FIRST DIVERGENCE: step {first['step']} rank "
                     f"{first['rank']} tensor {first['tensor']}")
    elif first["kind"] == "stream":
        lines.append(f"FIRST DIVERGENCE: request {first['trace']} "
                     f"attempt {first['attempt']}")
    else:
        lines.append(f"FIRST DIVERGENCE: {first['tensor']}")
    for d in divs:
        where = (f"step {d['step']} rank {d['rank']}"
                 if d["kind"] == "step"
                 else f"request {d['trace']} attempt {d['attempt']}"
                 if d["kind"] == "stream" else f"handoff {d['index']}")
        lines.append(f"DIVERGED   {where:<28} {d['tensor']}  "
                     f"{d.get('a')} != {d.get('b')}")
    lines.append(f"{len(divs)} divergent row(s)")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="diff two golden determinism ledgers; exit 1 on "
                    "divergence")
    ap.add_argument("golden", help="baseline ledger (JSONL)")
    ap.add_argument("candidate", help="candidate ledger (JSONL)")
    ap.add_argument("--json", action="store_true",
                    help="emit the divergence list as JSON")
    args = ap.parse_args(argv)
    try:
        a = load_ledger(args.golden)
        b = load_ledger(args.candidate)
    except (OSError, ValueError) as e:
        print(f"ledger_diff: {e}", file=sys.stderr)
        return 2
    divs = diff_ledgers(a, b)
    if args.json:
        json.dump({"divergences": divs, "identical": not divs},
                  sys.stdout, indent=1)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(render_text(divs, args.golden, args.candidate))
    return 1 if divs else 0


if __name__ == "__main__":
    sys.exit(main())
