#!/bin/bash
# Round-4 on-chip evidence pack (VERDICT r3 item 1 — outranks everything).
# Differences from tools/tpu_watch.sh: results land INCREMENTALLY in a
# JSONL (a mid-pack tunnel wedge cannot lose earlier numbers), and the
# benches are ordered safe-first: the in-repo paged-attention Mosaic
# compile — the exact thing that wedged the tunnel for rounds 2-3 — runs
# DEAD LAST, after every other number (including the MFU sweep) is on
# disk. The decode bench first runs with PADDLE_TPU_PAGED_IMPL=jax
# (production kernel, no in-repo proof) so a decode number exists even if
# the in-repo proof wedges the pool.
set -u
cd /root/repo
PACK=/root/repo/BENCH_R4_PACK.jsonl
SWEEP=/root/repo/BENCH_SWEEP_R4.jsonl
LOG=/tmp/evidence_r4.log
: > "$PACK"; : > "$SWEEP"
echo "[evidence_r4] start $(date -u +%H:%M:%SZ)" >> "$LOG"

run_one() {  # run_one <outfile> <label> <env...>
  local out=$1 label=$2; shift 2
  local line
  line=$(env "$@" BENCH_PROBE_TIMEOUT=150 timeout 4800 python bench.py 2>>"$LOG" | tail -1)
  if ! printf '%s' "$line" | python -c 'import json,sys; json.loads(sys.stdin.read())' 2>/dev/null; then
    line='{"error": "bench produced no parseable JSON (timeout/kill?)"}'
  fi
  printf '{"label": "%s", "result": %s}\n' "$label" "$line" >> "$out"
  echo "[evidence_r4] $label -> $line" >> "$LOG"
}

# Phase A: safe benches (no unproven Mosaic compiles beyond flash
# attention, which passed on-chip in round 2).
run_one "$PACK" resnet               BENCH_MODEL=resnet
run_one "$PACK" llama_r2_shape       BENCH_MODEL=llama
run_one "$PACK" bert                 BENCH_MODEL=bert
run_one "$PACK" data_goodput         BENCH_MODEL=data
run_one "$PACK" resnet_loader        BENCH_MODEL=resnet BENCH_DATA=loader
run_one "$PACK" dispatch             BENCH_MODEL=dispatch

# Phase B: MFU sweep toward the >=35% target (VERDICT r3 item 2).
for cfg in \
  "BENCH_PRESET=1b BENCH_BATCH=4 BENCH_SEQ=2048 BENCH_REMAT=1" \
  "BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=1" \
  "BENCH_PRESET=1b BENCH_BATCH=16 BENCH_SEQ=2048 BENCH_REMAT=1" \
  "BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=4096 BENCH_REMAT=1" \
  "BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=0" \
  "BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=1 PADDLE_TPU_FA_BLOCK_Q=256" \
  "BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=1 PADDLE_TPU_FA_BLOCK_Q=256 PADDLE_TPU_FA_BLOCK_K=256" \
  "BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=4096 BENCH_REMAT=1 PADDLE_TPU_FA_BLOCK_Q=512" \
  "BENCH_BATCH=16 BENCH_SEQ=2048" \
  "BENCH_BATCH=32 BENCH_SEQ=1024" ; do
  line=$(env $cfg BENCH_MODEL=llama BENCH_PROBE_TIMEOUT=150 \
         timeout 4800 python bench.py 2>>"$LOG" | tail -1)
  if ! printf '%s' "$line" | python -c 'import json,sys; json.loads(sys.stdin.read())' 2>/dev/null; then
    line='{"error": "bench produced no parseable JSON (timeout/kill?)"}'
  fi
  echo "{\"config\": \"$cfg\", \"result\": $line}" >> "$SWEEP"
  echo "[evidence_r4] sweep $cfg -> $line" >> "$LOG"
done

# Phase C: decode via the production jax kernel (skip the in-repo proof
# entirely — BENCH_CHILD=1 bypasses the orchestrator's prove step).
line=$(env BENCH_CHILD=1 BENCH_MODEL=llama_decode PADDLE_TPU_PAGED_IMPL=jax \
       PADDLE_TPU_KERNEL_GUARD=trust timeout 2400 python bench.py 2>>"$LOG" | tail -1)
if ! printf '%s' "$line" | python -c 'import json,sys; json.loads(sys.stdin.read())' 2>/dev/null; then
  line='{"error": "decode(jax impl) produced no parseable JSON"}'
fi
printf '{"label": "llama_decode_jax_impl", "result": %s}\n' "$line" >> "$PACK"
echo "[evidence_r4] llama_decode_jax_impl -> $line" >> "$LOG"

# Phase D (RISKY, last): prove the in-repo paged kernel in a disposable
# subprocess; if it passes, capture the in-repo-kernel decode number.
echo "[evidence_r4] proving in-repo paged_attention (risky)" >> "$LOG"
if timeout 500 python -m paddle_tpu.utils.guarded_compile prove paged_attention --timeout 420 >> "$LOG" 2>&1; then
  echo '{"label": "paged_attention_proof", "result": {"proved": true}}' >> "$PACK"
  run_one "$PACK" llama_decode_inrepo BENCH_MODEL=llama_decode
else
  echo '{"label": "paged_attention_proof", "result": {"proved": false}}' >> "$PACK"
  echo "[evidence_r4] in-repo paged kernel did NOT prove; see log" >> "$LOG"
fi

# Assemble the session JSON from the pack.
python - <<'EOF'
import json
results = []
for path in ("/root/repo/BENCH_R4_PACK.jsonl",):
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                results.append(json.loads(line))
with open("/root/repo/BENCH_TPU_SESSION_R4.json", "w") as f:
    json.dump({"session": "round4", "results": results}, f, indent=1)
print("assembled", len(results), "results")
EOF
echo "[evidence_r4] done $(date -u +%H:%M:%SZ)" >> "$LOG"
