"""Bench record comparator: diff two ``BENCH_*.json`` records with
per-metric direction + threshold rules and exit 1 on regression.

The bench trajectory (``BENCH_r*.json``, ``bench.py``'s one-line JSON)
is only useful if a regression between two records is *mechanically*
detectable — a human eyeballing "26.1 vs 24.9 images/sec" does not
scale to the aux-metric surface (phase fractions, peak bytes, p95s,
speedups). This tool knows which direction each metric should move:

* direction is inferred from the metric name (``DIRECTION_RULES`` —
  ``*_per_sec``/``*speedup``/``mfu*``/``*recover_ratio*`` are
  higher-better, ``*_ms``/``*_bytes``/``*waste*``/``*overhead*``/
  ``*time_to_recover*`` are lower-better; ``*controller_actions*`` is
  an action COUNT — churn is workload-shaped, so it is informational);
  unknown metrics are reported as info, never failed;
* a metric regresses when it moves in the bad direction by more than
  the threshold (default 10%, per-metric overrides via
  ``--rule name=higher|lower[:pct]``);
* input records are ``bench.py`` output dicts, driver wrappers with a
  ``parsed``/``result`` key, or lists (last record wins); nested dicts
  flatten to dotted keys.

Usage::

    python tools/bench_compare.py OLD.json NEW.json
    python tools/bench_compare.py --threshold 5 --html diff.html A.json B.json
    python tools/bench_compare.py --rule train_peak_bytes=lower:25 A.json B.json

Exit codes: 0 ok, 1 regression(s), 2 usage/input error. Same import
discipline as ``fleet_console.py``: stdlib-only, no jax/numpy — this
runs on a laptop against records scp'd off the fleet.
"""
from __future__ import annotations

import argparse
import html as _html
import json
import os
import sys

DEFAULT_THRESHOLD_PCT = 10.0

#: (substring, direction) — first match wins, checked in order. More
#: specific entries go first (``waste_ratio`` before ``ratio``).
DIRECTION_RULES = [
    ("telemetry_export_overhead", "lower"),
    ("scrape_age", "lower"),
    ("overhead_pct", "lower"),
    # steady-state serving recompiles must be ZERO; any rise is shape
    # churn past the declared buckets (warmup compile seconds are the
    # cold-start budget — also lower-better, via the _s suffix rule)
    ("recompiles_per_1k", "lower"),
    ("post_warmup_misses", "lower"),
    ("waste_ratio", "lower"),
    ("qblock_step_ratio", "lower"),
    ("weight_bytes_ratio", "lower"),
    ("forwards_per_token", "lower"),
    ("forwards_per_tick", "lower"),
    ("recover_ratio", "higher"),
    # tiered KV / long-context serving: host-tier TTFT win on prefix
    # re-admission and sep-prefill prompt throughput are the point of
    # the tier — both must not sink (explicit entries so they never
    # fall through to a suffix rule)
    ("kv_tier_hit_speedup", "higher"),
    ("long_context_tokens_per_s", "higher"),
    ("kv_tier_ttft", "lower"),
    ("controller_actions", "ignore"),
    ("time_to_recover", "lower"),
    ("wire_bytes", "lower"),
    ("peak_bytes", "lower"),
    ("per_sec", "higher"),
    ("per_s", "higher"),
    ("throughput", "higher"),
    ("tokens_per", "higher"),
    ("samples_per", "higher"),
    ("images/sec", "higher"),
    ("speedup", "higher"),
    ("goodput", "higher"),
    ("hit_rate", "higher"),
    ("acceptance", "higher"),
    ("mfu", "higher"),
    ("capacity_ratio", "higher"),
    ("compression_ratio", "higher"),
    ("sessions", "higher"),
]

#: (suffix, direction) — matched against the END of the name only, so
#: ``_s`` catches ``p99_latency_s`` without hijacking ``tokens_per_sec``
SUFFIX_RULES = [
    ("_bytes", "lower"),
    ("_ms", "lower"),
    ("_seconds", "lower"),
    ("_s", "lower"),
]

#: metric names that are configuration echoes, never judged
SKIP_KEYS = {"vs_baseline", "seed", "steps", "workers", "dp", "n",
             "rc", "value"}


def direction_of(name: str) -> "str | None":
    low = name.lower()
    for sub, d in DIRECTION_RULES:
        if sub in low:
            return d
    for suf, d in SUFFIX_RULES:
        if low.endswith(suf):
            return d
    return None


def load_record(path: str) -> dict:
    """Load one bench record: a flat bench.py dict, a driver wrapper
    ({"parsed": ...} / {"result": ...}), or a list (last wins)."""
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, list):
        data = data[-1] if data else {}
    for key in ("parsed", "result"):
        if isinstance(data, dict) and isinstance(data.get(key), dict):
            data = data[key]
    if not isinstance(data, dict):
        raise ValueError(f"{path}: not a bench record")
    return data


def flatten(rec: dict, prefix="") -> dict:
    """Numeric leaves as dotted keys. The headline ``value`` is keyed
    by the record's ``metric`` name so direction inference applies to
    what the number *is*, not to the word 'value'. String leaves named
    ``*_digest`` (content digests, e.g. ``serving_token_digest``) are
    kept too — they compare exact-match, so output-content drift fails
    the diff like a perf regression would."""
    out: dict = {}
    metric = rec.get("metric") if not prefix else None
    for k, v in rec.items():
        if k in SKIP_KEYS and not (k == "value" and metric):
            continue
        key = f"{prefix}{k}"
        if k == "value" and metric:
            key = str(metric)
        if isinstance(v, bool):
            continue
        if isinstance(v, (int, float)):
            out[key] = float(v)
        elif isinstance(v, str) and k.lower().endswith("_digest"):
            out[key] = v
        elif isinstance(v, dict):
            out.update(flatten(v, prefix=f"{key}."))
    return out


def parse_rule_overrides(specs) -> dict:
    """``--rule name=higher|lower[:pct]`` → {name: (direction, pct)}."""
    rules = {}
    for spec in specs or ():
        name, _, rest = spec.partition("=")
        if not name or not rest:
            raise ValueError(f"bad --rule {spec!r} "
                             "(want name=higher|lower[:pct])")
        d, _, pct = rest.partition(":")
        if d not in ("higher", "lower", "ignore"):
            raise ValueError(f"bad direction in --rule {spec!r} "
                             "(higher/lower/ignore)")
        rules[name] = (d, float(pct) if pct else None)
    return rules


def compare(old: dict, new: dict, threshold_pct=DEFAULT_THRESHOLD_PCT,
            overrides=None) -> list:
    """Row per metric present in BOTH records:
    ``{metric, old, new, delta_pct, direction, status}`` where status is
    ``ok`` / ``improved`` / ``REGRESSED`` / ``info`` (no direction)."""
    overrides = overrides or {}
    a, b = flatten(old), flatten(new)
    rows = []
    for name in sorted(set(a) & set(b)):
        va, vb = a[name], b[name]
        direction, pct = overrides.get(
            name, (direction_of(name), None))
        if isinstance(va, str) or isinstance(vb, str):
            # content digests: exact match or regression — no threshold
            status = ("info" if direction == "ignore"
                      else "ok" if va == vb else "REGRESSED")
            rows.append({"metric": name, "old": va, "new": vb,
                         "delta_pct": 0.0 if va == vb else 100.0,
                         "direction": "exact", "threshold_pct": 0.0,
                         "status": status})
            continue
        pct = threshold_pct if pct is None else pct
        if va == 0:
            delta = 0.0 if vb == 0 else float("inf") * (1 if vb > 0 else -1)
        else:
            delta = (vb - va) / abs(va) * 100.0
        if direction in (None, "ignore"):
            status = "info"
        else:
            worse = -delta if direction == "higher" else delta
            if worse > pct:
                status = "REGRESSED"
            elif worse < -pct:
                status = "improved"
            else:
                status = "ok"
        rows.append({"metric": name, "old": va, "new": vb,
                     "delta_pct": delta, "direction": direction or "?",
                     "threshold_pct": pct, "status": status})
    return rows


def fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def render_text(rows, old_path, new_path) -> str:
    out = [f"bench compare: {os.path.basename(old_path)} -> "
           f"{os.path.basename(new_path)}"]
    if not rows:
        out.append("(no comparable numeric metrics)")
        return "\n".join(out) + "\n"
    w = max(len(r["metric"]) for r in rows)
    for r in rows:
        d = ("+inf" if r["delta_pct"] == float("inf")
             else f"{r['delta_pct']:+.2f}%")
        out.append(f"{r['status']:<10} {r['metric']:<{w}}  "
                   f"{fmt(r['old'])} -> {fmt(r['new'])}  ({d}, "
                   f"{r['direction']} better, thr {r['threshold_pct']:g}%)")
    bad = [r for r in rows if r["status"] == "REGRESSED"]
    out.append(f"{len(rows)} metric(s) compared, {len(bad)} regression(s)")
    return "\n".join(out) + "\n"


def render_html(rows, old_path, new_path) -> str:
    def esc(x):
        return _html.escape(str(x))

    parts = ["<!doctype html><html><head><meta charset='utf-8'>",
             "<title>bench compare</title><style>",
             "body{font-family:monospace;background:#111;color:#ddd;"
             "padding:1em}",
             "table{border-collapse:collapse}",
             "td,th{padding:2px 10px;text-align:left;"
             "border-bottom:1px solid #333}",
             ".REGRESSED{color:#f66;font-weight:bold}",
             ".improved{color:#6f6}",
             ".info{color:#888}",
             "</style></head><body>",
             f"<h1>bench compare</h1><p>{esc(os.path.basename(old_path))}"
             f" &rarr; {esc(os.path.basename(new_path))}</p>",
             "<table><tr><th>status</th><th>metric</th><th>old</th>"
             "<th>new</th><th>delta</th><th>direction</th></tr>"]
    for r in rows:
        d = ("+inf" if r["delta_pct"] == float("inf")
             else f"{r['delta_pct']:+.2f}%")
        parts.append(
            f"<tr class='{esc(r['status'])}'><td>{esc(r['status'])}</td>"
            f"<td>{esc(r['metric'])}</td><td>{fmt(r['old'])}</td>"
            f"<td>{fmt(r['new'])}</td><td>{esc(d)}</td>"
            f"<td>{esc(r['direction'])}</td></tr>")
    parts.append("</table></body></html>")
    return "".join(parts)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="compare two bench JSON records; exit 1 on regression")
    ap.add_argument("old", help="baseline bench record (JSON)")
    ap.add_argument("new", help="candidate bench record (JSON)")
    ap.add_argument("--threshold", type=float,
                    default=DEFAULT_THRESHOLD_PCT,
                    help="regression threshold in percent (default 10)")
    ap.add_argument("--rule", action="append", metavar="NAME=DIR[:PCT]",
                    help="per-metric override, e.g. "
                         "train_peak_bytes=lower:25 or foo=ignore")
    ap.add_argument("--html", metavar="PATH",
                    help="also write an HTML diff table")
    args = ap.parse_args(argv)
    try:
        old = load_record(args.old)
        new = load_record(args.new)
        overrides = parse_rule_overrides(args.rule)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"bench_compare: {e}", file=sys.stderr)
        return 2
    rows = compare(old, new, threshold_pct=args.threshold,
                   overrides=overrides)
    if not rows:
        print("bench_compare: no comparable numeric metrics",
              file=sys.stderr)
        return 2
    sys.stdout.write(render_text(rows, args.old, args.new))
    if args.html:
        with open(args.html, "w") as f:
            f.write(render_html(rows, args.old, args.new))
    return 1 if any(r["status"] == "REGRESSED" for r in rows) else 0


if __name__ == "__main__":
    sys.exit(main())
