#!/bin/bash
# Watch for TPU tunnel recovery; the moment a backend probe succeeds,
# run the three benches back-to-back and record their JSON lines.
# Round-3 context: the axon pool was wedged at round start (VERDICT item 1
# asks for benches FIRST — this is the closest achievable: benches fire in
# the first healthy window). Only one process may touch the TPU, so this
# watcher is the sole chip client until it exits.
# `tpu_watch.sh metrics [path]`: follow the live metrics_text() dump the
# flight-recorder watchdog rewrites each poll (PADDLE_METRICS_TEXT_PATH;
# see docs/OBSERVABILITY.md "Flight recorder & distributed diagnosis").
if [ "$1" = "metrics" ]; then
  exec tail -F "${2:-${PADDLE_METRICS_TEXT_PATH:-/tmp/paddle_metrics.prom}}"
fi

OUT=${1:-/root/repo/BENCH_TPU_SESSION.json}
LOG=/tmp/tpu_watch.log
cd /root/repo
echo "[tpu_watch] start $(date -u +%H:%M:%SZ)" >> "$LOG"
while true; do
  if timeout 150 python -c "import jax; assert jax.default_backend() not in ('cpu',); print('OK', jax.devices())" >> "$LOG" 2>&1; then
    echo "[tpu_watch] TPU reachable $(date -u +%H:%M:%SZ); running benches" >> "$LOG"
    {
      echo '{"session": "round4", "captured_at": "'"$(date -u +%Y-%m-%dT%H:%M:%SZ)"'", "results": ['
      first=1
      for spec in resnet llama llama_decode bert data resnet+BENCH_DATA=loader; do
        mode=${spec%%+*}
        extra=""
        [ "$spec" != "$mode" ] && extra=${spec#*+}
        # bench.py bounds its own children (probe 150s + attempts
        # 1500/900 + cpu fallback 1200, killed on expiry by
        # subprocess.run); 4800s is a backstop only, so it can't fire
        # mid-run and orphan a TPU-holding child while the loop moves on.
        line=$(env $extra BENCH_MODEL=$mode BENCH_PROBE_TIMEOUT=150 timeout 4800 python bench.py 2>>"$LOG" | tail -1)
        echo "[tpu_watch] $spec -> $line" >> "$LOG"
        [ -z "$line" ] && line='{"metric": "'$mode'", "value": null, "error": "bench timed out"}'
        if [ $first -eq 1 ]; then first=0; else echo ','; fi
        echo "$line"
      done
      echo ']}'
    } > "$OUT.tmp" && mv "$OUT.tmp" "$OUT"
    echo "[tpu_watch] done; results in $OUT" >> "$LOG"
    # MFU sweep toward the 40% north star (VERDICT round-2 item 2):
    # 1B-class llama over batch/seq/remat; each line records the mfu aux
    SWEEP=/root/repo/BENCH_SWEEP_R4.jsonl
    : > "$SWEEP"
    for cfg in \
      "BENCH_PRESET=1b BENCH_BATCH=4 BENCH_SEQ=2048 BENCH_REMAT=1" \
      "BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=1" \
      "BENCH_PRESET=1b BENCH_BATCH=16 BENCH_SEQ=2048 BENCH_REMAT=1" \
      "BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=4096 BENCH_REMAT=1" \
      "BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=0" \
      "BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=1 PADDLE_TPU_FA_BLOCK_Q=256" \
      "BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=1 PADDLE_TPU_FA_BLOCK_Q=256 PADDLE_TPU_FA_BLOCK_K=256" \
      "BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=4096 BENCH_REMAT=1 PADDLE_TPU_FA_BLOCK_Q=512" \
      "BENCH_BATCH=16 BENCH_SEQ=2048" \
      "BENCH_BATCH=32 BENCH_SEQ=1024" ; do
      line=$(env $cfg BENCH_MODEL=llama BENCH_PROBE_TIMEOUT=150 \
             timeout 4800 python bench.py 2>>"$LOG" | tail -1)
      # only splice verified-JSON into the sweep file — a timeout-kill
      # mid-print or stray stdout must not poison every later parse
      if ! printf '%s' "$line" | python -c 'import json,sys; json.loads(sys.stdin.read())' 2>/dev/null; then
        line='{"error": "bench run produced no parseable JSON (timeout/kill?)"}'
      fi
      echo "{\"config\": \"$cfg\", \"result\": $line}" >> "$SWEEP"
      echo "[tpu_watch] sweep $cfg -> $line" >> "$LOG"
    done
    echo "[tpu_watch] sweep done -> $SWEEP" >> "$LOG"
    exit 0
  fi
  echo "[tpu_watch] probe failed $(date -u +%H:%M:%SZ); retry in 300s" >> "$LOG"
  sleep 300
done
