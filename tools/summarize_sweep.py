#!/usr/bin/env python
"""Summarize an MFU sweep JSONL (BENCH_SWEEP_R*.jsonl): one line per
config sorted by MFU, plus the winner in BASELINE.md-ready form.

Usage: python tools/summarize_sweep.py [sweep.jsonl]
"""
import json
import sys


def main(path="/root/repo/BENCH_SWEEP_R5.jsonl"):
    rows = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                row = json.loads(line)
                r = row.get("result", {})
                rows.append((row.get("config", "?"), r))
    except FileNotFoundError:
        print(f"no sweep file at {path}")
        return 1
    scored = []
    for cfg, r in rows:
        if r.get("value") is None:
            scored.append((None, cfg, r.get("error", "no value")[:80]))
        else:
            scored.append((r.get("mfu_pct"), cfg,
                           f"{r['value']:.0f} tok/s  mfu={r.get('mfu_pct')}%"
                           f"  chip={r.get('chip', r.get('backend'))}"))
    scored.sort(key=lambda t: (t[0] is None, -(t[0] or 0)))
    for mfu, cfg, desc in scored:
        print(f"{cfg:38s} {desc}")
    winners = [t for t in scored if t[0] is not None]
    if winners:
        mfu, cfg, desc = winners[0]
        print(f"\nWINNER: {cfg} -> {desc}")
        if mfu >= 35:
            print("north-star gate: >=35% MFU MET")
        else:
            print(f"north-star gate: {mfu}% < 35% — keep tuning")
    return 0


if __name__ == "__main__":
    sys.exit(main(*sys.argv[1:]))
