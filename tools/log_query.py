"""Query the structured event logs (``paddle_tpu/profiler/eventlog.py``
JSONL): filter and JOIN by trace id, replica, kind and time window
across any number of per-replica log files — one request's whole story
(admission -> route -> kill -> requeue -> delivered) stays greppable
after every process that served it is gone.

Usage:
    python tools/log_query.py events.jsonl                    # everything
    python tools/log_query.py --trace req-1a2b-000003 r*/events.jsonl
    python tools/log_query.py --replica r1 --kind requeue,delivered *.jsonl
    python tools/log_query.py --since 1754300000 --until 1754300060 a.jsonl
    python tools/log_query.py --json --trace req-... a.jsonl b.jsonl

Records are merged from every input file (globs ok, rotated ``.1``
siblings included via ``--rotated``) and printed oldest-first, each
stamped with the file it came from — the cross-replica join is the sort.
Same import discipline as ``fleet_console.py``: stdlib-only, no jax —
this must run on a laptop against logs scp'd off the fleet.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys


def load_records(paths, include_rotated=False):
    """[(path, record), ...] from every readable JSONL input. Torn or
    non-JSON lines are skipped with a stderr note (a log being written
    this instant may legitimately end mid-line only if the writer is
    broken — the eventlog's single-write contract makes these rare)."""
    files = []
    for pattern in paths:
        hits = sorted(glob.glob(pattern)) or [pattern]
        for path in hits:
            files.append(path)
            if include_rotated and os.path.exists(path + ".1"):
                files.append(path + ".1")
    out = []
    for path in files:
        try:
            f = open(path, errors="replace")
        except OSError as e:
            print(f"log_query: cannot read {path}: {e}", file=sys.stderr)
            continue
        with f:
            for lineno, line in enumerate(f, start=1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    print(f"log_query: {path}:{lineno}: skipping "
                          f"non-JSON line", file=sys.stderr)
                    continue
                if isinstance(rec, dict):
                    out.append((path, rec))
    return out


def match(rec, trace=None, replica=None, kinds=None, since=None,
          until=None):
    if trace is not None and str(rec.get("trace_id")) != str(trace):
        return False
    if replica is not None and str(rec.get("replica")) != str(replica):
        return False
    if kinds and str(rec.get("kind")) not in kinds:
        return False
    ts = rec.get("ts")
    if since is not None and (ts is None or ts < since):
        return False
    if until is not None and (ts is None or ts > until):
        return False
    return True


def query(paths, trace=None, replica=None, kinds=None, since=None,
          until=None, include_rotated=False):
    """The joined, time-ordered record list (each with ``_file``)."""
    rows = []
    for path, rec in load_records(paths, include_rotated=include_rotated):
        if match(rec, trace=trace, replica=replica, kinds=kinds,
                 since=since, until=until):
            rec = dict(rec, _file=os.path.basename(path))
            rows.append(rec)
    rows.sort(key=lambda r: (r.get("ts") or 0.0, r.get("kind", "")))
    return rows


_CORE = ("ts", "kind", "replica", "trace_id", "rank", "_file")


def format_row(rec) -> str:
    ts = rec.get("ts")
    extra = {k: v for k, v in rec.items() if k not in _CORE}
    detail = " ".join(f"{k}={v}" for k, v in sorted(extra.items()))
    return (f"{ts:.6f}  {rec.get('kind', '?'):<22} "
            f"replica={rec.get('replica') or '-':<10} "
            f"trace={rec.get('trace_id') or '-':<24} "
            f"[{rec.get('_file', '?')}]"
            + (f"  {detail}" if detail else ""))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="filter/join structured event logs by trace id, "
                    "replica, kind and time window")
    ap.add_argument("inputs", nargs="+",
                    help="eventlog JSONL files (globs ok)")
    ap.add_argument("--trace", help="only events of this trace id")
    ap.add_argument("--replica", help="only events of this replica")
    ap.add_argument("--kind",
                    help="comma-separated event kinds to keep")
    ap.add_argument("--since", type=float,
                    help="only events with ts >= SINCE (unix seconds)")
    ap.add_argument("--until", type=float,
                    help="only events with ts <= UNTIL (unix seconds)")
    ap.add_argument("--rotated", action="store_true",
                    help="also read each input's rotated .1 sibling")
    ap.add_argument("--json", action="store_true",
                    help="emit JSONL instead of aligned text")
    args = ap.parse_args(argv)
    kinds = (set(k.strip() for k in args.kind.split(",") if k.strip())
             if args.kind else None)
    rows = query(args.inputs, trace=args.trace, replica=args.replica,
                 kinds=kinds, since=args.since, until=args.until,
                 include_rotated=args.rotated)
    for rec in rows:
        if args.json:
            print(json.dumps(rec))
        else:
            print(format_row(rec))
    if not rows:
        print("log_query: no matching events", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
