#!/bin/bash
# Round-5 evidence pack runner (VERDICT r4 item 1: the proof round).
# Health-gated capture in the scripted SAFE order: plain-attention llama
# first (same op classes as the resnet/bert graphs that always compiled),
# novel-formulation compiles (xflash canary) LAST, and the in-repo Mosaic
# paged kernel proof at the very end of the session (wedge-riskiest).
# Results land incrementally in BENCH_R5_PACK.jsonl / BENCH_SWEEP_R5.jsonl
# and are re-assembled into BENCH_TPU_SESSION_R5.json after every row, so
# a wedge mid-pack loses nothing.
set -u
cd /root/repo
PACK=/root/repo/BENCH_R5_PACK.jsonl
SWEEP=/root/repo/BENCH_SWEEP_R5.jsonl
LOG=/tmp/evidence_r5.log
echo "[r5] start $(date -u +%H:%M:%SZ)" >> "$LOG"

assemble() {
  python - <<'EOF'
import json, os
rows = []
for path, kind in (("/root/repo/BENCH_R5_PACK.jsonl", "bench"),
                   ("/root/repo/BENCH_SWEEP_R5.jsonl", "sweep")):
    if not os.path.exists(path):
        continue
    by_key, order = {}, []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            k = row.get("label") or row.get("config")
            if k not in by_key:
                order.append(k)
            by_key[k] = row
    rows += [by_key[k] for k in order]
with open("/root/repo/BENCH_TPU_SESSION_R5.json", "w") as f:
    json.dump({"session": "round5", "results": rows}, f, indent=1)
print("assembled", len(rows), "rows")
EOF
}

wait_healthy() {
  while true; do
    if timeout 550 python -c "import jax; assert jax.default_backend()=='tpu'; import jax.numpy as jnp; (jnp.ones((64,64))@jnp.ones((64,64))).block_until_ready()" >/dev/null 2>&1; then
      echo "[r5] pool healthy $(date -u +%H:%M:%SZ)" >> "$LOG"; return 0
    fi
    echo "[r5] pool down $(date -u +%H:%M:%SZ); retry in 600s" >> "$LOG"
    sleep 600
  done
}

run_one() {  # run_one <label> <timeout> <env...>
  local label=$1 tmo=$2; shift 2
  wait_healthy
  local line
  line=$(env "$@" BENCH_PROVE=0 BENCH_PROBE_TIMEOUT=150 timeout "$tmo" python bench.py 2>>"$LOG" | tail -1)
  if ! printf '%s' "$line" | python -c 'import json,sys; json.loads(sys.stdin.read())' 2>/dev/null; then
    line='{"error": "bench produced no parseable JSON (timeout/kill?)"}'
  fi
  printf '{"label": "%s", "result": %s}\n' "$label" "$line" >> "$PACK"
  echo "[r5] $label -> $line" >> "$LOG"
  assemble >> "$LOG" 2>&1
}

sweep_one() {  # sweep_one <cfgstring> <env...>
  local cfg=$1; shift
  wait_healthy
  local line
  line=$(env "$@" BENCH_MODEL=llama BENCH_PROVE=0 BENCH_PROBE_TIMEOUT=150 \
         timeout 1500 python bench.py 2>>"$LOG" | tail -1)
  if ! printf '%s' "$line" | python -c 'import json,sys; json.loads(sys.stdin.read())' 2>/dev/null; then
    line='{"error": "bench run produced no parseable JSON (timeout/kill?)"}'
  fi
  echo "{\"config\": \"$cfg\", \"result\": $line}" >> "$SWEEP"
  echo "[r5] sweep $cfg -> $line" >> "$LOG"
  assemble >> "$LOG" 2>&1
}

# Phase A: headline benches, safest graphs first. Plain-attention llama
# before anything exotic; decode pinned to the pure-XLA tier.
run_one resnet           900  BENCH_MODEL=resnet
run_one llama_plain_attn 1500 BENCH_MODEL=llama FLAGS_use_flash_attention=0
run_one bert             1500 BENCH_MODEL=bert
run_one llama_decode_xla 1500 BENCH_MODEL=llama_decode PADDLE_TPU_PAGED_IMPL=xla FLAGS_use_flash_attention=0
run_one data_goodput     1200 BENCH_MODEL=data
run_one resnet_loader    1200 BENCH_MODEL=resnet BENCH_DATA=loader
run_one dispatch         1200 BENCH_MODEL=dispatch

# Phase B: MFU sweep at the 1b preset, plain attention, highest-expected-
# MFU configs first (playbook: bf16 state frees ~6.6 GB for no-remat
# arithmetic; accum = no-remat arithmetic at microbatch memory; dots
# policy saves projections; full remat pays +33% FLOPs).
sweep_one "1b b8 s2048 norem bf16state"   BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=0 BENCH_PARAM_DTYPE=bf16 FLAGS_use_flash_attention=0
sweep_one "1b b16 s2048 norem bf16state"  BENCH_PRESET=1b BENCH_BATCH=16 BENCH_SEQ=2048 BENCH_REMAT=0 BENCH_PARAM_DTYPE=bf16 FLAGS_use_flash_attention=0
sweep_one "1b b16 s2048 accum2 bf16state" BENCH_PRESET=1b BENCH_BATCH=16 BENCH_SEQ=2048 BENCH_REMAT=0 BENCH_ACCUM=2 BENCH_PARAM_DTYPE=bf16 FLAGS_use_flash_attention=0
sweep_one "1b b32 s2048 accum4 bf16state" BENCH_PRESET=1b BENCH_BATCH=32 BENCH_SEQ=2048 BENCH_REMAT=0 BENCH_ACCUM=4 BENCH_PARAM_DTYPE=bf16 FLAGS_use_flash_attention=0
sweep_one "1b b8 s2048 norem accum2"  BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=0 BENCH_ACCUM=2 FLAGS_use_flash_attention=0
sweep_one "1b b16 s2048 norem accum4" BENCH_PRESET=1b BENCH_BATCH=16 BENCH_SEQ=2048 BENCH_REMAT=0 BENCH_ACCUM=4 FLAGS_use_flash_attention=0
sweep_one "1b b4 s2048 dots plain"    BENCH_PRESET=1b BENCH_BATCH=4 BENCH_SEQ=2048 BENCH_REMAT=dots FLAGS_use_flash_attention=0
sweep_one "1b b8 s2048 dots accum2"   BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=dots BENCH_ACCUM=2 FLAGS_use_flash_attention=0
sweep_one "1b b4 s2048 remat plain"   BENCH_PRESET=1b BENCH_BATCH=4 BENCH_SEQ=2048 BENCH_REMAT=1 FLAGS_use_flash_attention=0
sweep_one "1b b8 s2048 remat plain"   BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=1 FLAGS_use_flash_attention=0
sweep_one "1b b8 s2048 norem plain"   BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=0 FLAGS_use_flash_attention=0
sweep_one "1b b4 s4096 dots chunked"  BENCH_PRESET=1b BENCH_BATCH=4 BENCH_SEQ=4096 BENCH_REMAT=dots PADDLE_TPU_XFA=0

# Phase C: xflash canary — ONE tiny isolated compile of the scan
# formulation (the round-4 wedge suspect). Only on success do scan-tier
# sweep rows run.
wait_healthy
echo "[r5] xflash canary (tiny, isolated)" >> "$LOG"
if timeout 600 python - >> "$LOG" 2>&1 <<'EOF'
import jax, jax.numpy as jnp
from paddle_tpu.ops.pallas.flash_attention import _xflash
import numpy as np
q = jnp.asarray(np.random.randn(1, 4, 1024, 64), jnp.bfloat16)
offs = jnp.zeros((2,), jnp.int32)
def f(q):
    return _xflash(q, q, q, offs, True, 0.125).sum()
v, g = jax.jit(jax.value_and_grad(f))(q)
jax.block_until_ready((v, g))
print("xflash canary OK", float(v))
EOF
then
  echo '{"label": "xflash_canary", "result": {"compiled": true}}' >> "$PACK"
  sweep_one "1b b8 s2048 remat xflash" BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=1
  sweep_one "1b b8 s4096 remat xflash" BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=4096 BENCH_REMAT=1
  sweep_one "1b b8 s2048 remat scanq"  BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=1 PADDLE_TPU_XFA=scanq
else
  echo '{"label": "xflash_canary", "result": {"compiled": false, "note": "scan-formulation compile hung/failed; sweep stays on plain+chunked tiers"}}' >> "$PACK"
fi
assemble >> "$LOG" 2>&1

# Phase D (VERY LAST — wedge-riskiest; VERDICT r4 item 6): prove the
# in-repo Mosaic paged-attention kernel via guarded_compile, then bench
# decode on it. A hang here costs nothing already captured.
wait_healthy
echo "[r5] in-repo paged kernel proof (guarded_compile, last)" >> "$LOG"
if timeout 900 python - >> "$LOG" 2>&1 <<'EOF'
from paddle_tpu.utils.guarded_compile import prove_all
print("paged proof:", prove_all(["paged_attention"]))
EOF
then
  run_one llama_decode_inrepo 1500 BENCH_MODEL=llama_decode PADDLE_TPU_PAGED_IMPL=inrepo
else
  echo '{"label": "paged_kernel_proof", "result": {"proved": false, "note": "guarded_compile subprocess failed/hung; decode stays on the pure-XLA tier (documented delegation)"}}' >> "$PACK"
fi
assemble >> "$LOG" 2>&1
echo "[r5] done $(date -u +%H:%M:%SZ)" >> "$LOG"
