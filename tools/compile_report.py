"""Compile-observatory reporter: per-family compile counts / wall
seconds / retrace causes from the structured event log, live ``/compile``
scrapes, or a diff of two runs.

The compile observatory (``paddle_tpu/profiler/compile_observatory.py``)
appends one event-log record per trace-cache **miss** (``kind:
"compile"``, ``src: "compile_observatory"``) carrying the program
family, the structured retrace cause ("arg `tokens` dim0 136∉{128,256}:
bucket miss", "static arg `weight_dtype` 'int8'→'bf16'", ...), the
compile wall seconds and the full argument signature. This tool folds
those records into the answers a recompile-storm page needs:

* which family is recompiling, how often, and how much wall time it ate;
* WHY — the top retrace causes, verbatim (the cause string names the
  exact argument and offending dimension, so it maps directly to the
  bucket/knob to fix);
* whether a change regressed compile counts (``--diff OLD NEW``: any
  family compiling more in NEW than OLD is a regression — steady-state
  serving recompiles must be zero).

Usage::

    python tools/compile_report.py EVENTS.jsonl              # one run
    python tools/compile_report.py --fleet HOST:P1,HOST:P2   # live scrape
    python tools/compile_report.py --diff OLD.jsonl NEW.jsonl
    python tools/compile_report.py --json EVENTS.jsonl

Exit codes: 0 ok (and --diff found no regression), 1 --diff regression,
2 usage/input error. Same import discipline as ``ledger_diff.py`` /
``bench_compare.py``: stdlib-only, no jax/numpy — this runs on a laptop
against logs scp'd off the fleet.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.request

#: how many distinct cause strings to print per family
TOP_CAUSES = 5


def load_events(path: str) -> list:
    """Observatory compile records (``kind == "compile"`` and ``src ==
    "compile_observatory"``) from one event-log JSONL file. Records the
    request tracer *tees* (``src: "trace"``) are span copies of the same
    misses and are deliberately skipped — counting both would double
    every miss."""
    out = []
    with open(path) as f:
        for ln, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{ln}: not JSON ({e})") from e
            if row.get("kind") != "compile":
                continue
            if row.get("src") != "compile_observatory":
                continue
            out.append(row)
    return out


def fold(records: list) -> dict:
    """``{family: {compiles, compile_s, causes: {cause: count}}}``."""
    fams: dict = {}
    for r in records:
        fam = str(r.get("family", "?"))
        d = fams.setdefault(fam, {"compiles": 0, "compile_s": 0.0,
                                  "causes": {}})
        d["compiles"] += 1
        try:
            d["compile_s"] += float(r.get("seconds") or 0.0)
        except (TypeError, ValueError):
            pass
        cause = str(r.get("cause", "?"))
        d["causes"][cause] = d["causes"].get(cause, 0) + 1
    return fams


def fetch_fleet(endpoints: list, timeout_s=3.0) -> dict:
    """Scrape every ``host:port`` endpoint's ``/compile`` route and fold
    the snapshots into the same per-family shape (plus undeclared-family
    drift). Endpoints that fail to answer are reported, not fatal."""
    fams: dict = {}
    undeclared: dict = {}
    errors: dict = {}
    for ep in endpoints:
        try:
            with urllib.request.urlopen(f"http://{ep}/compile",
                                        timeout=timeout_s) as resp:
                snap = json.loads(resp.read().decode(
                    "utf-8", errors="replace"))
        except Exception as e:
            errors[ep] = repr(e)
            continue
        inst = str(snap.get("instance", ep))
        for fam in snap.get("undeclared", ()):
            undeclared.setdefault(str(fam), []).append(inst)
        for name, f in (snap.get("families") or {}).items():
            d = fams.setdefault(name, {"compiles": 0, "compile_s": 0.0,
                                       "hits": 0, "causes": {},
                                       "instances": []})
            d["compiles"] += int(f.get("misses", 0))
            d["hits"] += int(f.get("hits", 0))
            d["compile_s"] += float(f.get("compile_s", 0.0))
            d["instances"].append(inst)
            for c in f.get("last_causes") or ():
                cause = (c.get("cause", "?") if isinstance(c, dict)
                         else str(c))
                d["causes"][cause] = d["causes"].get(cause, 0) + 1
    return {"families": fams, "undeclared": undeclared, "errors": errors}


def diff_folds(old: dict, new: dict) -> list:
    """Per-family compile-count regressions (NEW compiled more than
    OLD), worst first. Each: ``{family, old, new, delta, causes}`` with
    NEW's top causes attached — the storm's attribution."""
    out = []
    for fam in sorted(set(old) | set(new)):
        o = old.get(fam, {}).get("compiles", 0)
        n = new.get(fam, {}).get("compiles", 0)
        if n > o:
            causes = new.get(fam, {}).get("causes", {})
            top = sorted(causes.items(), key=lambda kv: -kv[1])
            out.append({"family": fam, "old": o, "new": n,
                        "delta": n - o,
                        "causes": [c for c, _ in top[:TOP_CAUSES]]})
    out.sort(key=lambda d: -d["delta"])
    return out


def _fmt_family_block(name, d, lines):
    lines.append(f"{name:<28} compiles={d['compiles']:<5} "
                 f"compile_s={d['compile_s']:.3f}"
                 + (f" hits={d['hits']}" if "hits" in d else ""))
    top = sorted(d.get("causes", {}).items(), key=lambda kv: -kv[1])
    for cause, count in top[:TOP_CAUSES]:
        lines.append(f"    {count:>4}x {cause}")
    extra = len(top) - TOP_CAUSES
    if extra > 0:
        lines.append(f"    ... {extra} more cause(s)")


def render_report(fams: dict, title: str) -> str:
    lines = [f"compile report: {title}"]
    if not fams:
        lines.append("no compile records")
        return "\n".join(lines) + "\n"
    total_c = sum(d["compiles"] for d in fams.values())
    total_s = sum(d["compile_s"] for d in fams.values())
    lines.append(f"{len(fams)} family(ies), {total_c} compile(s), "
                 f"{total_s:.3f}s compile wall time")
    for name in sorted(fams, key=lambda n: -fams[n]["compiles"]):
        _fmt_family_block(name, fams[name], lines)
    return "\n".join(lines) + "\n"


def render_fleet(view: dict) -> str:
    lines = [render_report(view["families"], "fleet /compile scrape")
             .rstrip("\n")]
    for fam, insts in sorted(view.get("undeclared", {}).items()):
        lines.append(f"DRIFT: family {fam!r} never declared "
                     f"(seen on {', '.join(insts)})")
    for ep, err in sorted(view.get("errors", {}).items()):
        lines.append(f"UNREACHABLE: {ep}: {err}")
    return "\n".join(lines) + "\n"


def render_diff(regs: list, a_path, b_path) -> str:
    lines = [f"compile diff: {os.path.basename(a_path)} -> "
             f"{os.path.basename(b_path)}"]
    if not regs:
        lines.append("no compile-count regressions")
        return "\n".join(lines) + "\n"
    for r in regs:
        lines.append(f"REGRESSED  {r['family']:<28} "
                     f"{r['old']} -> {r['new']} (+{r['delta']})")
        for cause in r["causes"]:
            lines.append(f"    cause: {cause}")
    lines.append(f"{len(regs)} regressed family(ies)")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="per-family compile counts/seconds/causes from the "
                    "event log, a live fleet, or a two-run diff")
    ap.add_argument("paths", nargs="*",
                    help="event-log JSONL file(s); with --diff exactly "
                         "two (OLD NEW)")
    ap.add_argument("--fleet", metavar="EP1,EP2",
                    help="scrape live host:port /compile endpoints "
                         "instead of reading logs")
    ap.add_argument("--diff", action="store_true",
                    help="diff two runs; exit 1 if any family compiled "
                         "more in NEW than OLD")
    ap.add_argument("--json", action="store_true",
                    help="emit the folded report as JSON")
    args = ap.parse_args(argv)

    if args.fleet:
        if args.paths or args.diff:
            print("compile_report: --fleet takes no log paths",
                  file=sys.stderr)
            return 2
        eps = [e.strip() for e in args.fleet.split(",") if e.strip()]
        view = fetch_fleet(eps)
        if args.json:
            json.dump(view, sys.stdout, indent=1, default=str)
            sys.stdout.write("\n")
        else:
            sys.stdout.write(render_fleet(view))
        return 0

    try:
        if args.diff:
            if len(args.paths) != 2:
                print("compile_report: --diff needs exactly OLD NEW",
                      file=sys.stderr)
                return 2
            old = fold(load_events(args.paths[0]))
            new = fold(load_events(args.paths[1]))
            regs = diff_folds(old, new)
            if args.json:
                json.dump({"regressions": regs, "ok": not regs},
                          sys.stdout, indent=1)
                sys.stdout.write("\n")
            else:
                sys.stdout.write(render_diff(regs, args.paths[0],
                                             args.paths[1]))
            return 1 if regs else 0
        if len(args.paths) != 1:
            print("compile_report: need one event-log path "
                  "(or --fleet / --diff)", file=sys.stderr)
            return 2
        fams = fold(load_events(args.paths[0]))
    except (OSError, ValueError) as e:
        print(f"compile_report: {e}", file=sys.stderr)
        return 2
    if args.json:
        json.dump({"families": fams}, sys.stdout, indent=1)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(render_report(fams,
                                       os.path.basename(args.paths[0])))
    return 0


if __name__ == "__main__":
    sys.exit(main())
