"""Fleet load console: render metric history, active alerts and
per-replica state as text sparklines (or a self-contained HTML page).

Inputs, auto-detected per file (globs ok):

* metric-history JSONL exports (``MetricsHistory.export_jsonl``, schema
  ``paddle_history/1``) — one sparkline per series, with rate for
  counters and min/mean/max for gauges;
* flight-recorder dumps (``flight_rank*.json``) — the ``alerts`` state
  provider (active rules + recent fire/clear transitions) and every
  fleet/engine state provider's replica table;
* replay reports (``ReplayReport.to_json``, schema
  ``paddle_replay_report/1``) — the goodput-under-burst /
  time-to-recover summary block.

Usage:
    python tools/fleet_console.py hist.jsonl
    python tools/fleet_console.py --match paddle_slo hist.jsonl flight_rank0.json
    python tools/fleet_console.py --html console.html hist.jsonl report.json

Same import discipline as ``trace_merge.py``: stdlib-only, no jax — this
must run on a laptop against files scp'd off the fleet.
"""
from __future__ import annotations

import argparse
import glob
import html as _html
import json
import os
import sys

BLOCKS = "▁▂▃▄▅▆▇█"


def sparkline(values, width=48):
    """Unicode sparkline of the last ``width`` values."""
    vals = list(values)[-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return BLOCKS[0] * len(vals)
    return "".join(BLOCKS[min(int((v - lo) / span * (len(BLOCKS) - 1e-9)),
                              len(BLOCKS) - 1)] for v in vals)


def counter_rate(points):
    """Reset-aware increase/second over the whole ring (the
    ``MetricsHistory.rate`` convention, reimplemented stdlib-only)."""
    if len(points) < 2:
        return 0.0
    inc = 0.0
    for (_, a), (_, b) in zip(points, points[1:]):
        inc += (b - a) if b >= a else b
    dt = points[-1][0] - points[0][0]
    return inc / dt if dt > 0 else 0.0


def fmt(v):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


# ---------------------------------------------------------------------------
# input classification
# ---------------------------------------------------------------------------


def load_inputs(paths):
    """Split inputs into (history series list, flight dumps, reports)."""
    series, dumps, reports = [], [], []
    for pattern in paths:
        hits = sorted(glob.glob(pattern)) or [pattern]
        for path in hits:
            with open(path) as f:
                first = f.readline()
                rest = f.read()
            try:
                head = json.loads(first)
            except ValueError:
                print(f"fleet_console: skipping {path} (not JSON)",
                      file=sys.stderr)
                continue
            schema = str(head.get("schema", "")) if isinstance(
                head, dict) else ""
            if schema.startswith("paddle_history"):
                for ln in rest.splitlines():
                    if ln.strip():
                        series.append(json.loads(ln))
            elif schema.startswith("paddle_replay_report"):
                reports.append((path, head))
            elif isinstance(head, dict) and ("events" in head
                                             or "state" in head):
                dumps.append((path, head))
            else:
                # a one-record file (report / dump written compact)
                try:
                    payload = json.loads(first + rest)
                except ValueError:
                    payload = head
                if isinstance(payload, dict) and str(
                        payload.get("schema", "")).startswith(
                        "paddle_replay_report"):
                    reports.append((path, payload))
                elif isinstance(payload, dict) and ("events" in payload
                                                    or "state" in payload):
                    dumps.append((path, payload))
                else:
                    print(f"fleet_console: skipping {path} (neither "
                          "history, flight dump, nor replay report)",
                          file=sys.stderr)
    return series, dumps, reports


def series_rows(series, match=None, width=48):
    rows = []
    for s in sorted(series, key=lambda r: (r["name"], r.get("labels", ""))):
        name = s["name"]
        labels = s.get("labels", "")
        disp = f"{name}{{{labels}}}" if labels else name
        if match and match not in disp:
            continue
        pts = [(p[0], p[1]) for p in s.get("points", [])]
        if not pts:
            continue
        vals = [v for _, v in pts]
        row = {"series": disp, "kind": s.get("kind", ""),
               "last": vals[-1], "min": min(vals), "max": max(vals),
               "mean": sum(vals) / len(vals), "n": len(vals),
               "spark": sparkline(vals, width=width)}
        if s.get("kind") == "counter":
            row["rate"] = counter_rate(pts)
        rows.append(row)
    return rows


def alert_sections(dumps):
    """Active alerts + transitions from every dump's ``alerts`` state
    provider."""
    active, transitions = {}, []
    for path, d in dumps:
        al = (d.get("state") or {}).get("alerts") or {}
        for name, ent in (al.get("active") or {}).items():
            active[name] = ent
        transitions.extend(al.get("recent_transitions") or [])
    transitions.sort(key=lambda t: t.get("t", 0))
    return active, transitions[-16:]


def replica_rows(dumps):
    """Per-replica state from fleet/engine state providers."""
    rows = []
    for path, d in dumps:
        for provider, payload in (d.get("state") or {}).items():
            if not isinstance(payload, dict):
                continue
            reps = payload.get("replicas")
            if isinstance(reps, dict):
                for rid, st in sorted(reps.items()):
                    if isinstance(st, dict):
                        rows.append({"replica": rid, "provider": provider,
                                     **st})
    return rows


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------


def render_text(rows, active, transitions, replicas, reports) -> str:
    out = []
    if rows:
        w = max(len(r["series"]) for r in rows)
        out.append("== metric history ==")
        for r in rows:
            stat = (f"rate {fmt(r.get('rate'))}/s"
                    if "rate" in r else
                    f"min {fmt(r['min'])} mean {fmt(r['mean'])} "
                    f"max {fmt(r['max'])}")
            out.append(f"{r['series']:<{w}}  {r['spark']}  "
                       f"last {fmt(r['last'])}  {stat}  [{r['n']} pts]")
    out.append("")
    out.append("== alerts ==")
    if active:
        for name, ent in sorted(active.items()):
            out.append(f"ACTIVE  {name}  severity={ent.get('severity')}  "
                       f"value={fmt(ent.get('value'))}  "
                       f"since t={fmt(ent.get('since'))}")
    else:
        out.append("(none active)")
    for tr in transitions:
        out.append(f"  {tr.get('action', '?'):<8} {tr.get('rule')}  "
                   f"t={fmt(tr.get('t'))}  value={fmt(tr.get('value'))}")
    if replicas:
        out.append("")
        out.append("== replicas ==")
        for r in replicas:
            out.append(
                f"{r.get('replica'):<6} role={r.get('role', '?'):<8} "
                f"alive={r.get('alive')} draining={r.get('draining')} "
                f"inflight={r.get('inflight')} "
                f"load_tokens={r.get('load_tokens')} "
                f"queue_depth={r.get('queue_depth')}")
    for path, rep in reports:
        out.append("")
        out.append(f"== replay report ({os.path.basename(path)}) ==")
        for key in ("preset", "seed", "requests", "ok", "statuses",
                    "goodput_under_burst", "p99_ttft_under_burst_s",
                    "p99_latency_s", "time_to_recover_s",
                    "schedule_digest"):
            if key in rep:
                out.append(f"  {key}: {fmt(rep[key]) if not isinstance(rep[key], dict) else json.dumps(rep[key])}")
    return "\n".join(out) + "\n"


def render_html(rows, active, transitions, replicas, reports) -> str:
    def esc(x):
        return _html.escape(str(x))

    parts = ["<!doctype html><html><head><meta charset='utf-8'>",
             "<title>fleet console</title><style>",
             "body{font-family:monospace;background:#111;color:#ddd;"
             "padding:1em}",
             "table{border-collapse:collapse}",
             "td,th{padding:2px 10px;text-align:left;"
             "border-bottom:1px solid #333}",
             ".spark{color:#6cf;font-size:14px}",
             ".active{color:#f66;font-weight:bold}",
             "h2{color:#9cf;margin-top:1.2em}",
             "</style></head><body><h1>fleet console</h1>"]
    if rows:
        parts.append("<h2>metric history</h2><table><tr><th>series</th>"
                     "<th>trend</th><th>last</th><th>stats</th>"
                     "<th>pts</th></tr>")
        for r in rows:
            stat = (f"rate {fmt(r.get('rate'))}/s" if "rate" in r
                    else f"min {fmt(r['min'])} mean {fmt(r['mean'])} "
                         f"max {fmt(r['max'])}")
            parts.append(
                f"<tr><td>{esc(r['series'])}</td>"
                f"<td class='spark'>{esc(r['spark'])}</td>"
                f"<td>{fmt(r['last'])}</td><td>{esc(stat)}</td>"
                f"<td>{r['n']}</td></tr>")
        parts.append("</table>")
    parts.append("<h2>alerts</h2>")
    if active:
        parts.append("<ul>")
        for name, ent in sorted(active.items()):
            parts.append(f"<li class='active'>ACTIVE {esc(name)} "
                         f"severity={esc(ent.get('severity'))} "
                         f"value={fmt(ent.get('value'))}</li>")
        parts.append("</ul>")
    else:
        parts.append("<p>(none active)</p>")
    if transitions:
        parts.append("<ul>")
        for tr in transitions:
            parts.append(f"<li>{esc(tr.get('action'))} "
                         f"{esc(tr.get('rule'))} t={fmt(tr.get('t'))}</li>")
        parts.append("</ul>")
    if replicas:
        parts.append("<h2>replicas</h2><table><tr><th>replica</th>"
                     "<th>role</th><th>alive</th><th>inflight</th>"
                     "<th>load</th><th>queue</th></tr>")
        for r in replicas:
            parts.append(
                f"<tr><td>{esc(r.get('replica'))}</td>"
                f"<td>{esc(r.get('role'))}</td>"
                f"<td>{esc(r.get('alive'))}</td>"
                f"<td>{esc(r.get('inflight'))}</td>"
                f"<td>{esc(r.get('load_tokens'))}</td>"
                f"<td>{esc(r.get('queue_depth'))}</td></tr>")
        parts.append("</table>")
    for path, rep in reports:
        parts.append(f"<h2>replay report ({esc(os.path.basename(path))})"
                     "</h2><table>")
        for key in ("preset", "seed", "requests", "ok",
                    "goodput_under_burst", "p99_ttft_under_burst_s",
                    "time_to_recover_s", "schedule_digest"):
            if key in rep:
                parts.append(f"<tr><td>{esc(key)}</td>"
                             f"<td>{esc(fmt(rep[key]))}</td></tr>")
        parts.append("</table>")
    parts.append("</body></html>")
    return "".join(parts)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render metric history / alerts / replica state")
    ap.add_argument("inputs", nargs="+",
                    help="history JSONL, flight dumps, replay reports "
                         "(globs ok)")
    ap.add_argument("--match", help="filter history series by substring")
    ap.add_argument("--width", type=int, default=48,
                    help="sparkline width (points)")
    ap.add_argument("--html", metavar="PATH",
                    help="write a self-contained HTML page instead of "
                         "text on stdout")
    args = ap.parse_args(argv)
    series, dumps, reports = load_inputs(args.inputs)
    if not series and not dumps and not reports:
        print("fleet_console: no usable inputs", file=sys.stderr)
        return 2
    rows = series_rows(series, match=args.match, width=args.width)
    active, transitions = alert_sections(dumps)
    replicas = replica_rows(dumps)
    if args.html:
        text = render_html(rows, active, transitions, replicas, reports)
        with open(args.html, "w") as f:
            f.write(text)
        print(f"fleet_console: {len(rows)} series, {len(active)} active "
              f"alert(s) -> {args.html}")
    else:
        sys.stdout.write(render_text(rows, active, transitions, replicas,
                                     reports))
    return 0


if __name__ == "__main__":
    sys.exit(main())
