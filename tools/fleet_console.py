"""Fleet load console: render metric history, active alerts and
per-replica state as text sparklines (or a self-contained HTML page).

Inputs, auto-detected per file (globs ok):

* metric-history JSONL exports (``MetricsHistory.export_jsonl``, schema
  ``paddle_history/1``) — one sparkline per series, with rate for
  counters and min/mean/max for gauges;
* flight-recorder dumps (``flight_rank*.json``) — the ``alerts`` state
  provider (active rules + recent fire/clear transitions), every
  fleet/engine state provider's replica table, and the
  ``fleet_controller`` provider's action timeline (action, reason,
  trigger metric value, cooldown state, quarantine/degradation
  posture);
* replay reports (``ReplayReport.to_json``, schema
  ``paddle_replay_report/1``) — the goodput-under-burst /
  time-to-recover summary block.

Usage:
    python tools/fleet_console.py hist.jsonl
    python tools/fleet_console.py --match paddle_slo hist.jsonl flight_rank0.json
    python tools/fleet_console.py --html console.html hist.jsonl report.json

Same import discipline as ``trace_merge.py``: stdlib-only, no jax — this
must run on a laptop against files scp'd off the fleet.
"""
from __future__ import annotations

import argparse
import glob
import html as _html
import json
import os
import sys

BLOCKS = "▁▂▃▄▅▆▇█"


def sparkline(values, width=48):
    """Unicode sparkline of the last ``width`` values."""
    vals = list(values)[-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return BLOCKS[0] * len(vals)
    return "".join(BLOCKS[min(int((v - lo) / span * (len(BLOCKS) - 1e-9)),
                              len(BLOCKS) - 1)] for v in vals)


def counter_rate(points):
    """Reset-aware increase/second over the whole ring (the
    ``MetricsHistory.rate`` convention, reimplemented stdlib-only)."""
    if len(points) < 2:
        return 0.0
    inc = 0.0
    for (_, a), (_, b) in zip(points, points[1:]):
        inc += (b - a) if b >= a else b
    dt = points[-1][0] - points[0][0]
    return inc / dt if dt > 0 else 0.0


def fmt(v):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


# ---------------------------------------------------------------------------
# input classification
# ---------------------------------------------------------------------------


def load_inputs(paths):
    """Split inputs into (history series list, flight dumps, reports)."""
    series, dumps, reports = [], [], []
    for pattern in paths:
        hits = sorted(glob.glob(pattern)) or [pattern]
        for path in hits:
            with open(path) as f:
                first = f.readline()
                rest = f.read()
            try:
                head = json.loads(first)
            except ValueError:
                print(f"fleet_console: skipping {path} (not JSON)",
                      file=sys.stderr)
                continue
            schema = str(head.get("schema", "")) if isinstance(
                head, dict) else ""
            if schema.startswith("paddle_history"):
                for ln in rest.splitlines():
                    if ln.strip():
                        series.append(json.loads(ln))
            elif schema.startswith("paddle_replay_report"):
                reports.append((path, head))
            elif isinstance(head, dict) and ("events" in head
                                             or "state" in head):
                dumps.append((path, head))
            else:
                # a one-record file (report / dump written compact)
                try:
                    payload = json.loads(first + rest)
                except ValueError:
                    payload = head
                if isinstance(payload, dict) and str(
                        payload.get("schema", "")).startswith(
                        "paddle_replay_report"):
                    reports.append((path, payload))
                elif isinstance(payload, dict) and ("events" in payload
                                                    or "state" in payload):
                    dumps.append((path, payload))
                else:
                    print(f"fleet_console: skipping {path} (neither "
                          "history, flight dump, nor replay report)",
                          file=sys.stderr)
    return series, dumps, reports


def series_rows(series, match=None, width=48):
    rows = []
    for s in sorted(series, key=lambda r: (r["name"], r.get("labels", ""))):
        name = s["name"]
        labels = s.get("labels", "")
        disp = f"{name}{{{labels}}}" if labels else name
        if match and match not in disp:
            continue
        pts = [(p[0], p[1]) for p in s.get("points", [])]
        if not pts:
            continue
        vals = [v for _, v in pts]
        row = {"series": disp, "kind": s.get("kind", ""),
               "last": vals[-1], "min": min(vals), "max": max(vals),
               "mean": sum(vals) / len(vals), "n": len(vals),
               "spark": sparkline(vals, width=width)}
        if s.get("kind") == "counter":
            row["rate"] = counter_rate(pts)
        rows.append(row)
    return rows


def alert_sections(dumps):
    """Active alerts + transitions from every dump's ``alerts`` state
    provider."""
    active, transitions = {}, []
    for path, d in dumps:
        al = (d.get("state") or {}).get("alerts") or {}
        for name, ent in (al.get("active") or {}).items():
            active[name] = ent
        transitions.extend(al.get("recent_transitions") or [])
    transitions.sort(key=lambda t: t.get("t", 0))
    return active, transitions[-16:]


def replica_rows(dumps):
    """Per-replica state from fleet/engine state providers."""
    rows = []
    for path, d in dumps:
        for provider, payload in (d.get("state") or {}).items():
            if not isinstance(payload, dict):
                continue
            reps = payload.get("replicas")
            if isinstance(reps, dict):
                for rid, st in sorted(reps.items()):
                    if isinstance(st, dict):
                        rows.append({"replica": rid, "provider": provider,
                                     **st})
    return rows


def controller_sections(dumps):
    """Controller action timeline + posture from every dump's
    ``fleet_controller`` state provider (any provider payload carrying
    ``recent_actions`` qualifies — the same duck-typing as the replica
    tables). Returns (actions oldest-first, posture summary)."""
    actions, posture = [], {}
    for path, d in dumps:
        for provider, payload in (d.get("state") or {}).items():
            if not isinstance(payload, dict):
                continue
            acts = payload.get("recent_actions")
            if not isinstance(acts, list):
                continue
            actions.extend(a for a in acts if isinstance(a, dict))
            for key in ("cooldowns", "quarantined", "degraded",
                        "shed_tenants", "max_new_cap", "warm_pool",
                        "failures"):
                if key in payload:
                    posture[key] = payload[key]
    actions.sort(key=lambda a: a.get("t", 0))
    return actions[-32:], posture


def controller_lines(actions, posture):
    """Text lines for the controller timeline (shared by render_text)."""
    out = []
    if not actions and not posture:
        return out
    out.append("")
    out.append("== controller actions ==")
    if actions:
        for a in actions:
            out.append(
                f"  t={fmt(a.get('t')):<10} {a.get('action', '?'):<11} "
                f"reason={a.get('reason', '?'):<16} "
                f"target={fmt(a.get('target'))}  "
                f"value={fmt(a.get('value'))}  "
                f"cooldown_s={fmt(a.get('cooldown_s'))}")
    else:
        out.append("  (no actions recorded)")
    if posture:
        cool = posture.get("cooldowns") or {}
        cool_s = ", ".join(f"{k}={fmt(v)}s"
                           for k, v in sorted(cool.items())) or "all ready"
        out.append(f"  cooldowns: {cool_s}")
        if posture.get("quarantined"):
            out.append(f"  QUARANTINED: "
                       f"{', '.join(posture['quarantined'])}")
        if posture.get("degraded"):
            shed = ", ".join(posture.get("shed_tenants") or []) or "-"
            out.append(f"  DEGRADED: shed tenants [{shed}] "
                       f"max_new_cap={fmt(posture.get('max_new_cap'))}")
        if "warm_pool" in posture:
            out.append(f"  warm pool: {posture['warm_pool']} engine(s)")
    return out


# ---------------------------------------------------------------------------
# live scrape mode (--scrape): merged view against a RUNNING fleet
# ---------------------------------------------------------------------------


def _load_scrape_mod():
    """Standalone-load ``paddle_tpu/profiler/scrape.py`` by file path —
    its module level is stdlib-only by contract, so the console gets the
    strict exposition parser + instance merge without importing
    paddle_tpu (and thus without jax)."""
    import importlib.util
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                        "paddle_tpu", "profiler", "scrape.py")
    spec = importlib.util.spec_from_file_location("_paddle_scrape", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def scrape_endpoints(endpoints, timeout_s=2.0):
    """Fetch + parse ``/metrics`` and ``/healthz`` from each
    ``host:port``; returns (by_instance families, health rows)."""
    import urllib.request
    from urllib.error import HTTPError, URLError

    mod = _load_scrape_mod()
    by_instance, health = {}, []
    for ep in endpoints:
        instance, _, addr = ep.partition("=")
        if not addr:
            instance, addr = ep, ep
        row = {"instance": instance, "endpoint": addr, "ok": False}
        try:
            by_instance[instance] = mod.fetch_metrics(addr,
                                                      timeout_s=timeout_s)
            row["ok"] = True
        except Exception as e:
            row["error"] = repr(e)
        try:
            with urllib.request.urlopen(f"http://{addr}/healthz",
                                        timeout=timeout_s) as resp:
                row["healthz"] = json.loads(resp.read()).get("ok")
        except HTTPError as e:
            try:
                row["healthz"] = json.loads(e.read()).get("ok")
            except ValueError:
                row["healthz"] = False
        except (URLError, OSError, ValueError):
            row["healthz"] = None
        health.append(row)
    return mod.merge_instances(by_instance), health


def render_scrape(merged, health, match=None) -> str:
    out = ["== live fleet (scraped) =="]
    for row in health:
        status = "UP" if row["ok"] else "DOWN"
        hz = {True: "healthy", False: "UNHEALTHY",
              None: "no healthz"}[row.get("healthz")]
        line = (f"{row['instance']:<12} {row['endpoint']:<22} "
                f"{status:<5} {hz}")
        if row.get("error"):
            line += f"  {row['error']}"
        out.append(line)
    out.append("")
    out.append("== merged metrics ==")
    for name in sorted(merged):
        fam = merged[name]
        for key in sorted(fam.get("series", {})):
            disp = f"{name}{{{key}}}" if key else name
            if match and match not in disp:
                continue
            val = fam["series"][key]
            if isinstance(val, dict):      # histogram snapshot
                out.append(f"{disp}  count={fmt(val.get('count'))} "
                           f"sum={fmt(val.get('sum'))}")
            else:
                out.append(f"{disp}  {fmt(val)}")
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------


def render_text(rows, active, transitions, replicas, reports,
                ctl_actions=(), ctl_posture=None) -> str:
    out = []
    if rows:
        w = max(len(r["series"]) for r in rows)
        out.append("== metric history ==")
        for r in rows:
            stat = (f"rate {fmt(r.get('rate'))}/s"
                    if "rate" in r else
                    f"min {fmt(r['min'])} mean {fmt(r['mean'])} "
                    f"max {fmt(r['max'])}")
            out.append(f"{r['series']:<{w}}  {r['spark']}  "
                       f"last {fmt(r['last'])}  {stat}  [{r['n']} pts]")
    out.append("")
    out.append("== alerts ==")
    if active:
        for name, ent in sorted(active.items()):
            out.append(f"ACTIVE  {name}  severity={ent.get('severity')}  "
                       f"value={fmt(ent.get('value'))}  "
                       f"since t={fmt(ent.get('since'))}")
    else:
        out.append("(none active)")
    for tr in transitions:
        out.append(f"  {tr.get('action', '?'):<8} {tr.get('rule')}  "
                   f"t={fmt(tr.get('t'))}  value={fmt(tr.get('value'))}")
    if replicas:
        out.append("")
        out.append("== replicas ==")
        for r in replicas:
            out.append(
                f"{r.get('replica'):<6} role={r.get('role', '?'):<8} "
                f"alive={r.get('alive')} draining={r.get('draining')} "
                f"inflight={r.get('inflight')} "
                f"load_tokens={r.get('load_tokens')} "
                f"queue_depth={r.get('queue_depth')}")
    out.extend(controller_lines(ctl_actions, ctl_posture or {}))
    for path, rep in reports:
        out.append("")
        out.append(f"== replay report ({os.path.basename(path)}) ==")
        for key in ("preset", "seed", "requests", "ok", "statuses",
                    "goodput_under_burst", "p99_ttft_under_burst_s",
                    "p99_latency_s", "time_to_recover_s",
                    "schedule_digest"):
            if key in rep:
                out.append(f"  {key}: {fmt(rep[key]) if not isinstance(rep[key], dict) else json.dumps(rep[key])}")
    return "\n".join(out) + "\n"


def render_html(rows, active, transitions, replicas, reports,
                ctl_actions=(), ctl_posture=None) -> str:
    def esc(x):
        return _html.escape(str(x))

    parts = ["<!doctype html><html><head><meta charset='utf-8'>",
             "<title>fleet console</title><style>",
             "body{font-family:monospace;background:#111;color:#ddd;"
             "padding:1em}",
             "table{border-collapse:collapse}",
             "td,th{padding:2px 10px;text-align:left;"
             "border-bottom:1px solid #333}",
             ".spark{color:#6cf;font-size:14px}",
             ".active{color:#f66;font-weight:bold}",
             "h2{color:#9cf;margin-top:1.2em}",
             "</style></head><body><h1>fleet console</h1>"]
    if rows:
        parts.append("<h2>metric history</h2><table><tr><th>series</th>"
                     "<th>trend</th><th>last</th><th>stats</th>"
                     "<th>pts</th></tr>")
        for r in rows:
            stat = (f"rate {fmt(r.get('rate'))}/s" if "rate" in r
                    else f"min {fmt(r['min'])} mean {fmt(r['mean'])} "
                         f"max {fmt(r['max'])}")
            parts.append(
                f"<tr><td>{esc(r['series'])}</td>"
                f"<td class='spark'>{esc(r['spark'])}</td>"
                f"<td>{fmt(r['last'])}</td><td>{esc(stat)}</td>"
                f"<td>{r['n']}</td></tr>")
        parts.append("</table>")
    parts.append("<h2>alerts</h2>")
    if active:
        parts.append("<ul>")
        for name, ent in sorted(active.items()):
            parts.append(f"<li class='active'>ACTIVE {esc(name)} "
                         f"severity={esc(ent.get('severity'))} "
                         f"value={fmt(ent.get('value'))}</li>")
        parts.append("</ul>")
    else:
        parts.append("<p>(none active)</p>")
    if transitions:
        parts.append("<ul>")
        for tr in transitions:
            parts.append(f"<li>{esc(tr.get('action'))} "
                         f"{esc(tr.get('rule'))} t={fmt(tr.get('t'))}</li>")
        parts.append("</ul>")
    if replicas:
        parts.append("<h2>replicas</h2><table><tr><th>replica</th>"
                     "<th>role</th><th>alive</th><th>inflight</th>"
                     "<th>load</th><th>queue</th></tr>")
        for r in replicas:
            parts.append(
                f"<tr><td>{esc(r.get('replica'))}</td>"
                f"<td>{esc(r.get('role'))}</td>"
                f"<td>{esc(r.get('alive'))}</td>"
                f"<td>{esc(r.get('inflight'))}</td>"
                f"<td>{esc(r.get('load_tokens'))}</td>"
                f"<td>{esc(r.get('queue_depth'))}</td></tr>")
        parts.append("</table>")
    if ctl_actions or ctl_posture:
        parts.append("<h2>controller actions</h2><table><tr><th>t</th>"
                     "<th>action</th><th>reason</th><th>target</th>"
                     "<th>value</th><th>cooldown_s</th></tr>")
        for a in ctl_actions:
            parts.append(
                f"<tr><td>{fmt(a.get('t'))}</td>"
                f"<td>{esc(a.get('action'))}</td>"
                f"<td>{esc(a.get('reason'))}</td>"
                f"<td>{esc(fmt(a.get('target')))}</td>"
                f"<td>{fmt(a.get('value'))}</td>"
                f"<td>{fmt(a.get('cooldown_s'))}</td></tr>")
        parts.append("</table>")
        posture = ctl_posture or {}
        if posture.get("quarantined"):
            parts.append("<p class='active'>QUARANTINED: "
                         f"{esc(', '.join(posture['quarantined']))}</p>")
        if posture.get("degraded"):
            parts.append("<p class='active'>DEGRADED: shed "
                         f"{esc(', '.join(posture.get('shed_tenants') or []))}"
                         f" max_new_cap={fmt(posture.get('max_new_cap'))}"
                         "</p>")
    for path, rep in reports:
        parts.append(f"<h2>replay report ({esc(os.path.basename(path))})"
                     "</h2><table>")
        for key in ("preset", "seed", "requests", "ok",
                    "goodput_under_burst", "p99_ttft_under_burst_s",
                    "time_to_recover_s", "schedule_digest"):
            if key in rep:
                parts.append(f"<tr><td>{esc(key)}</td>"
                             f"<td>{esc(fmt(rep[key]))}</td></tr>")
        parts.append("</table>")
    parts.append("</body></html>")
    return "".join(parts)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render metric history / alerts / replica state")
    ap.add_argument("inputs", nargs="*",
                    help="history JSONL, flight dumps, replay reports "
                         "(globs ok)")
    ap.add_argument("--match", help="filter history series by substring")
    ap.add_argument("--width", type=int, default=48,
                    help="sparkline width (points)")
    ap.add_argument("--html", metavar="PATH",
                    help="write a self-contained HTML page instead of "
                         "text on stdout")
    ap.add_argument("--scrape", metavar="EP[,EP...]",
                    help="LIVE mode: scrape running telemetry endpoints "
                         "('host:port' or 'name=host:port', comma-"
                         "separated) and render the merged fleet view")
    ap.add_argument("--rounds", type=int, default=1,
                    help="scrape rounds to render (with --scrape)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="seconds between scrape rounds")
    args = ap.parse_args(argv)
    if args.scrape:
        import time as _time
        endpoints = [e.strip() for e in args.scrape.split(",")
                     if e.strip()]
        for i in range(max(args.rounds, 1)):
            if i:
                _time.sleep(args.interval)
            merged, health = scrape_endpoints(endpoints)
            sys.stdout.write(render_scrape(merged, health,
                                           match=args.match))
            sys.stdout.flush()
        return 0
    if not args.inputs:
        print("fleet_console: need inputs (or --scrape)", file=sys.stderr)
        return 2
    series, dumps, reports = load_inputs(args.inputs)
    if not series and not dumps and not reports:
        print("fleet_console: no usable inputs", file=sys.stderr)
        return 2
    rows = series_rows(series, match=args.match, width=args.width)
    active, transitions = alert_sections(dumps)
    replicas = replica_rows(dumps)
    ctl_actions, ctl_posture = controller_sections(dumps)
    if args.html:
        text = render_html(rows, active, transitions, replicas, reports,
                           ctl_actions, ctl_posture)
        with open(args.html, "w") as f:
            f.write(text)
        print(f"fleet_console: {len(rows)} series, {len(active)} active "
              f"alert(s) -> {args.html}")
    else:
        sys.stdout.write(render_text(rows, active, transitions, replicas,
                                     reports, ctl_actions, ctl_posture))
    return 0


if __name__ == "__main__":
    sys.exit(main())
