#!/bin/bash
# Round-4 evidence pack, take 4.
# Take-3 state (2026-07-31): pool healthy at 03:17Z, resnet landed on-chip
# (135,140 img/s — committed), then the FIRST llama compile hung the remote
# pool: with BENCH_PROVE=0 the llama step routes attention through the new
# pure-XLA scan-formulation flash (_xflash, scan-in-scan + custom_vjp) whose
# server-side XLA compile never returned. Parallel probes confirm the pool
# serves nothing while that compile is pending, and killing the client does
# not free it.
# This runner therefore (a) health-gates every step, (b) pins llama to the
# PLAIN attention path first (FLAGS_use_flash_attention=0 — same op classes
# as the resnet/bert graphs that compile fine), (c) canaries the scan
# formulation in ONE tiny isolated compile before any sweep config uses it,
# and (d) keeps every result incremental on disk.
set -u
cd /root/repo
PACK=/root/repo/BENCH_R4_PACK.jsonl      # resnet row already present
SWEEP=/root/repo/BENCH_SWEEP_R4.jsonl
LOG=/tmp/evidence_r4d.log
echo "[r4d] start $(date -u +%H:%M:%SZ)" >> "$LOG"

wait_healthy() {
  # Gentle probing: a killed probe mid-claim may itself leave "grant
  # unclaimed" state on the relay, so probe rarely and give each probe
  # long enough to ride out a slow grant (the 04:35Z experiment showed
  # 580 s is still not enough when wedged — but a recovering pool
  # answers in seconds).
  while true; do
    if timeout 550 python -c "import jax; assert jax.default_backend()=='tpu'; import jax.numpy as jnp; (jnp.ones((64,64))@jnp.ones((64,64))).block_until_ready()" >/dev/null 2>&1; then
      echo "[r4d] pool healthy $(date -u +%H:%M:%SZ)" >> "$LOG"; return 0
    fi
    echo "[r4d] pool down $(date -u +%H:%M:%SZ); retry in 600s" >> "$LOG"
    sleep 600
  done
}

run_one() {  # run_one <outfile> <label> <timeout> <env...>
  local out=$1 label=$2 tmo=$3; shift 3
  wait_healthy
  local line
  line=$(env "$@" BENCH_PROVE=0 BENCH_PROBE_TIMEOUT=150 timeout "$tmo" python bench.py 2>>"$LOG" | tail -1)
  if ! printf '%s' "$line" | python -c 'import json,sys; json.loads(sys.stdin.read())' 2>/dev/null; then
    line='{"error": "bench produced no parseable JSON (timeout/kill?)"}'
  fi
  printf '{"label": "%s", "result": %s}\n' "$label" "$line" >> "$out"
  echo "[r4d] $label -> $line" >> "$LOG"
}

sweep_one() {  # sweep_one <cfgstring> <env...>
  local cfg=$1; shift
  wait_healthy
  local line
  line=$(env "$@" BENCH_MODEL=llama BENCH_PROVE=0 BENCH_PROBE_TIMEOUT=150 \
         timeout 1500 python bench.py 2>>"$LOG" | tail -1)
  if ! printf '%s' "$line" | python -c 'import json,sys; json.loads(sys.stdin.read())' 2>/dev/null; then
    line='{"error": "bench run produced no parseable JSON (timeout/kill?)"}'
  fi
  echo "{\"config\": \"$cfg\", \"result\": $line}" >> "$SWEEP"
  echo "[r4d] sweep $cfg -> $line" >> "$LOG"
}

# Phase A: flagship + remaining headline benches, plain-attention llama first.
run_one "$PACK" llama_plain_attn 1500 BENCH_MODEL=llama FLAGS_use_flash_attention=0
run_one "$PACK" bert             1500 BENCH_MODEL=bert
run_one "$PACK" llama_decode_xla 1500 BENCH_MODEL=llama_decode PADDLE_TPU_PAGED_IMPL=xla FLAGS_use_flash_attention=0
run_one "$PACK" data_goodput     1200 BENCH_MODEL=data
run_one "$PACK" resnet_loader    1200 BENCH_MODEL=resnet BENCH_DATA=loader
run_one "$PACK" dispatch         1200 BENCH_MODEL=dispatch

# Phase B: MFU sweep, plain attention (1b preset; seq<=2048 fits without
# flash-memory behavior; remat recomputes the scores in bwd).
sweep_one "1b b4 s2048 remat plain"  BENCH_PRESET=1b BENCH_BATCH=4 BENCH_SEQ=2048 BENCH_REMAT=1 FLAGS_use_flash_attention=0
sweep_one "1b b8 s2048 remat plain"  BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=1 FLAGS_use_flash_attention=0
sweep_one "1b b16 s2048 remat plain" BENCH_PRESET=1b BENCH_BATCH=16 BENCH_SEQ=2048 BENCH_REMAT=1 FLAGS_use_flash_attention=0
sweep_one "1b b8 s2048 dots plain"   BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=dots FLAGS_use_flash_attention=0
sweep_one "1b b16 s2048 dots plain"  BENCH_PRESET=1b BENCH_BATCH=16 BENCH_SEQ=2048 BENCH_REMAT=dots FLAGS_use_flash_attention=0
sweep_one "1b b8 s2048 norem plain"  BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=0 FLAGS_use_flash_attention=0
sweep_one "1b b16 s1024 norem plain" BENCH_PRESET=1b BENCH_BATCH=16 BENCH_SEQ=1024 BENCH_REMAT=0 FLAGS_use_flash_attention=0
sweep_one "r2shape b16 s2048 plain"  BENCH_BATCH=16 BENCH_SEQ=2048 FLAGS_use_flash_attention=0
sweep_one "r2shape b32 s1024 plain"  BENCH_BATCH=32 BENCH_SEQ=1024 FLAGS_use_flash_attention=0

# Phase C: canary the scan-formulation xflash in ONE tiny isolated compile
# (disposable subprocess, small shapes). Only if THIS returns do any
# sweep configs use the scan path.
wait_healthy
echo "[r4d] xflash canary (tiny, isolated)" >> "$LOG"
if timeout 600 python - >> "$LOG" 2>&1 <<'EOF'
import jax, jax.numpy as jnp
from paddle_tpu.ops.pallas.flash_attention import _xflash
import numpy as np
q = jnp.asarray(np.random.randn(1, 4, 1024, 64), jnp.bfloat16)
offs = jnp.zeros((2,), jnp.int32)
def f(q):
    return _xflash(q, q, q, offs, True, 0.125).sum()
v, g = jax.jit(jax.value_and_grad(f))(q)
jax.block_until_ready((v, g))
print("xflash canary OK", float(v))
EOF
then
  echo '{"label": "xflash_canary", "result": {"compiled": true}}' >> "$PACK"
  sweep_one "1b b8 s2048 remat xflash"        BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=1
  sweep_one "1b b8 s4096 remat xflash"        BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=4096 BENCH_REMAT=1
  sweep_one "1b b8 s2048 remat xflash q256"   BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=1 PADDLE_TPU_XFA_BLOCK_Q=256
  sweep_one "1b b8 s2048 remat xflash q1024k2048" BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=1 PADDLE_TPU_XFA_BLOCK_Q=1024 PADDLE_TPU_XFA_BLOCK_K=2048
else
  echo '{"label": "xflash_canary", "result": {"compiled": false, "note": "scan-formulation compile hung/failed; sweep stays on plain+chunked tiers"}}' >> "$PACK"
  # long-seq config on the chunked tier instead (flash memory profile,
  # no scan formulation)
  sweep_one "1b b8 s4096 remat chunked" BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=4096 BENCH_REMAT=1 PADDLE_TPU_XFA=0
fi

python - <<'EOF'
import json
# dedup by label keeping the LAST row — earlier takes leave failed rows
# (e.g. take-3's llama timeout) that a later take supersedes
by_label = {}
order = []
with open("/root/repo/BENCH_R4_PACK.jsonl") as f:
    for line in f:
        line = line.strip()
        if not line:
            continue
        row = json.loads(line)
        if row["label"] not in by_label:
            order.append(row["label"])
        by_label[row["label"]] = row
results = [by_label[l] for l in order]
with open("/root/repo/BENCH_TPU_SESSION_R4.json", "w") as f:
    json.dump({"session": "round4", "results": results}, f, indent=1)
print("assembled", len(results), "results")
EOF
echo "[r4d] done $(date -u +%H:%M:%SZ)" >> "$LOG"

# Appended while the runner waited on pool recovery (append-only is safe
# for an executing bash script): a lower-memory dots-policy row — the
# b8/b16 dots rows above may exceed 16 GB HBM at the 1b preset — plus a
# re-assembly so these rows land in the session JSON too.
sweep_one "1b b4 s2048 dots plain" BENCH_PRESET=1b BENCH_BATCH=4 BENCH_SEQ=2048 BENCH_REMAT=dots FLAGS_use_flash_attention=0
sweep_one "1b b4 s4096 dots chunked" BENCH_PRESET=1b BENCH_BATCH=4 BENCH_SEQ=4096 BENCH_REMAT=dots PADDLE_TPU_XFA=0
python - <<'EOF2'
import json
by_label, order = {}, []
with open("/root/repo/BENCH_R4_PACK.jsonl") as f:
    for line in f:
        line = line.strip()
        if not line:
            continue
        row = json.loads(line)
        if row["label"] not in by_label:
            order.append(row["label"])
        by_label[row["label"]] = row
with open("/root/repo/BENCH_TPU_SESSION_R4.json", "w") as f:
    json.dump({"session": "round4",
               "results": [by_label[l] for l in order]}, f, indent=1)
print("re-assembled")
EOF2
echo "[r4d] appended rows done $(date -u +%H:%M:%SZ)" >> "$LOG"
# scanq-tier rows (appended): constant-graph-size scan tier
sweep_one "1b b8 s2048 remat scanq" BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=1 PADDLE_TPU_XFA=scanq
sweep_one "1b b8 s4096 remat scanq" BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=4096 BENCH_REMAT=1 PADDLE_TPU_XFA=scanq
python - <<'EOF3'
import json
by_label, order = {}, []
with open("/root/repo/BENCH_R4_PACK.jsonl") as f:
    for line in f:
        line = line.strip()
        if not line:
            continue
        row = json.loads(line)
        if row["label"] not in by_label:
            order.append(row["label"])
        by_label[row["label"]] = row
with open("/root/repo/BENCH_TPU_SESSION_R4.json", "w") as f:
    json.dump({"session": "round4",
               "results": [by_label[l] for l in order]}, f, indent=1)
print("re-assembled (scanq rows)")
EOF3
echo "[r4d] scanq rows done $(date -u +%H:%M:%SZ)" >> "$LOG"
# grad-accumulation rows (appended): no-remat at effective batch 8/16 —
# avoids the +33% recompute FLOPs that cap full-remat MFU
sweep_one "1b b8 s2048 norem accum2" BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=0 BENCH_ACCUM=2 FLAGS_use_flash_attention=0
sweep_one "1b b8 s2048 dots accum2"  BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=dots BENCH_ACCUM=2 FLAGS_use_flash_attention=0
sweep_one "1b b16 s2048 norem accum4" BENCH_PRESET=1b BENCH_BATCH=16 BENCH_SEQ=2048 BENCH_REMAT=0 BENCH_ACCUM=4 FLAGS_use_flash_attention=0
python - <<'EOF4'
import json
by_label, order = {}, []
with open("/root/repo/BENCH_R4_PACK.jsonl") as f:
    for line in f:
        line = line.strip()
        if not line:
            continue
        row = json.loads(line)
        if row["label"] not in by_label:
            order.append(row["label"])
        by_label[row["label"]] = row
with open("/root/repo/BENCH_TPU_SESSION_R4.json", "w") as f:
    json.dump({"session": "round4",
               "results": [by_label[l] for l in order]}, f, indent=1)
print("re-assembled (accum rows)")
EOF4
echo "[r4d] accum rows done $(date -u +%H:%M:%SZ)" >> "$LOG"
