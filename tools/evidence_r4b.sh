#!/bin/bash
# Round-4 evidence pack, take 2 — ZERO Mosaic compiles.
# Take 1 (tools/evidence_r4.sh) proved the wedge mechanism: the tunnel was
# healthy (ResNet 117k img/s on-chip), then the flash-attention canary — the
# SAME kernel that passed on-chip in round 2 — hung its Mosaic compile and
# wedged the remote pool for everything after. Killing the disposable
# subprocess does not unwedge the server. So: this runner waits for the pool
# to recover, then captures every number on pure-XLA paths (BENCH_PROVE=0;
# quarantined Pallas kernels use their XLA fallbacks; decode forces
# PADDLE_TPU_PAGED_IMPL=xla). No proof, no canary, no Mosaic — ever.
set -u
cd /root/repo
PACK=/root/repo/BENCH_R4_PACK.jsonl      # appends after take-1's resnet row
SWEEP=/root/repo/BENCH_SWEEP_R4.jsonl
LOG=/tmp/evidence_r4b.log
echo "[r4b] start $(date -u +%H:%M:%SZ)" >> "$LOG"

run_one() {  # run_one <outfile> <label> <env...>
  local out=$1 label=$2; shift 2
  local line
  line=$(env "$@" BENCH_PROVE=0 BENCH_PROBE_TIMEOUT=150 timeout 4000 python bench.py 2>>"$LOG" | tail -1)
  if ! printf '%s' "$line" | python -c 'import json,sys; json.loads(sys.stdin.read())' 2>/dev/null; then
    line='{"error": "bench produced no parseable JSON (timeout/kill?)"}'
  fi
  printf '{"label": "%s", "result": %s}\n' "$label" "$line" >> "$out"
  echo "[r4b] $label -> $line" >> "$LOG"
}

# Wait for pool recovery.
while true; do
  if timeout 150 python -c "import jax; assert jax.default_backend() == 'tpu'; import jax.numpy as jnp; (jnp.ones((8,8)) @ jnp.ones((8,8))).block_until_ready()" >> "$LOG" 2>&1; then
    echo "[r4b] TPU healthy $(date -u +%H:%M:%SZ)" >> "$LOG"
    break
  fi
  echo "[r4b] probe failed $(date -u +%H:%M:%SZ); retry in 300s" >> "$LOG"
  sleep 300
done

run_one "$PACK" llama_xla_fallback   BENCH_MODEL=llama
run_one "$PACK" bert                 BENCH_MODEL=bert
run_one "$PACK" llama_decode_xla     BENCH_MODEL=llama_decode PADDLE_TPU_PAGED_IMPL=xla
run_one "$PACK" data_goodput         BENCH_MODEL=data
run_one "$PACK" resnet_loader        BENCH_MODEL=resnet BENCH_DATA=loader
run_one "$PACK" dispatch             BENCH_MODEL=dispatch

# MFU sweep on the XLA-attention path (VERDICT r3 item 2).
for cfg in \
  "BENCH_PRESET=1b BENCH_BATCH=4 BENCH_SEQ=2048 BENCH_REMAT=1" \
  "BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=1" \
  "BENCH_PRESET=1b BENCH_BATCH=16 BENCH_SEQ=2048 BENCH_REMAT=1" \
  "BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=4096 BENCH_REMAT=1" \
  "BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=0" \
  "BENCH_PRESET=1b BENCH_BATCH=16 BENCH_SEQ=1024 BENCH_REMAT=0" \
  "BENCH_BATCH=16 BENCH_SEQ=2048" \
  "BENCH_BATCH=32 BENCH_SEQ=1024" \
  "BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=1 PADDLE_TPU_XFA_BLOCK_Q=256" \
  "BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=1 PADDLE_TPU_XFA_BLOCK_Q=512 PADDLE_TPU_XFA_BLOCK_K=512" \
  "BENCH_PRESET=1b BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_REMAT=1 PADDLE_TPU_XFA_BLOCK_Q=1024 PADDLE_TPU_XFA_BLOCK_K=2048" ; do
  line=$(env $cfg BENCH_MODEL=llama BENCH_PROVE=0 BENCH_PROBE_TIMEOUT=150 \
         timeout 4000 python bench.py 2>>"$LOG" | tail -1)
  if ! printf '%s' "$line" | python -c 'import json,sys; json.loads(sys.stdin.read())' 2>/dev/null; then
    line='{"error": "bench run produced no parseable JSON (timeout/kill?)"}'
  fi
  echo "{\"config\": \"$cfg xla-attn\", \"result\": $line}" >> "$SWEEP"
  echo "[r4b] sweep $cfg -> $line" >> "$LOG"
done

python - <<'EOF'
import json
results = []
with open("/root/repo/BENCH_R4_PACK.jsonl") as f:
    for line in f:
        line = line.strip()
        if line:
            results.append(json.loads(line))
with open("/root/repo/BENCH_TPU_SESSION_R4.json", "w") as f:
    json.dump({"session": "round4", "results": results}, f, indent=1)
print("assembled", len(results), "results")
EOF
echo "[r4b] done $(date -u +%H:%M:%SZ)" >> "$LOG"
