"""Merge per-rank flight-recorder dumps / chrome traces into one view.

Two input shapes, auto-detected per file:

* chrome traces (``{"traceEvents": [...]}``, e.g. the Profiler's
  ``worker_*.pt.trace.json`` per-rank exports) — merged into ONE trace
  with one pid per rank (``--trace out.json``);
* flight-recorder dumps (``flight_rank*.json``, schema
  ``paddle_flight_recorder/1``) — merged into a cross-rank
  desync/straggler report (``--report out.json``) that names the rank
  and collective seq id a hang is stuck on.

The rank of a file comes from its payload (dumps carry ``rank``) or
from a ``rank<N>`` substring in the filename, else its position.

``--request <trace_id>`` filters the merged chrome trace down to ONE
request's flow (every event whose ``args.trace_id`` matches, plus its
flow arrows and the process-name metadata of the lanes it touched) — the
single-request view of a disaggregated prefill->handoff->decode journey.

Usage:
    python tools/trace_merge.py --trace merged.json rank*.trace.json
    python tools/trace_merge.py --trace one.json --request req-1a2b-000003 \
        replica*.trace.json
    python tools/trace_merge.py --report report.json flight_rank*.json
    python tools/trace_merge.py --report r.json --trace t.json <mixed...>
"""
from __future__ import annotations

import argparse
import glob
import importlib.util
import json
import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_FR = None


def _fr():
    """The flight_recorder module. It is stdlib-only, so load it straight
    from its file — the CLI must not drag in jax just to merge JSON."""
    global _FR
    if _FR is None:
        mod = sys.modules.get("paddle_tpu.profiler.flight_recorder")
        if mod is not None:              # already imported (tests)
            _FR = mod
        else:
            path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "paddle_tpu", "profiler",
                                "flight_recorder.py")
            spec = importlib.util.spec_from_file_location(
                "_flight_recorder_cli", path)
            _FR = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(_FR)
    return _FR


_RT = None


def _rt():
    """The request_trace module, loaded stdlib-only from its file (its
    package-relative imports are all lazy) — same rule as :func:`_fr`."""
    global _RT
    if _RT is None:
        mod = sys.modules.get("paddle_tpu.profiler.request_trace")
        if mod is not None:              # already imported (tests)
            _RT = mod
        else:
            path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "paddle_tpu", "profiler",
                                "request_trace.py")
            spec = importlib.util.spec_from_file_location(
                "_request_trace_cli", path)
            _RT = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(_RT)
    return _RT


def _rank_of(path, payload, fallback):
    if isinstance(payload, dict) and isinstance(payload.get("rank"), int):
        return payload["rank"]
    m = re.search(r"rank[_-]?(\d+)", os.path.basename(path))
    return int(m.group(1)) if m else fallback


def load_inputs(paths):
    """Split the input files into ({rank: trace}, {rank: dump}).

    Three payload shapes are auto-detected: chrome traces, flight dumps,
    and per-request timeline records (schema ``paddle_request_trace/1``,
    as returned by ``paddle.profiler.request_timeline``) — the latter
    render into per-replica chrome lanes, several timelines sharing a
    replica merge onto one lane."""
    traces, dumps = {}, {}
    idx = 0
    for pattern in paths:
        hits = sorted(glob.glob(pattern)) or [pattern]
        for path in hits:
            with open(path) as f:
                payload = json.load(f)
            rank = _rank_of(path, payload, idx)
            idx += 1
            if isinstance(payload, dict) and "traceEvents" in payload:
                traces[rank] = payload
            elif isinstance(payload, dict) and str(
                    payload.get("schema", "")).startswith(
                    "paddle_request_trace"):
                for lane, t in _rt().timeline_to_chrome(payload).items():
                    dst = traces.setdefault(lane, {"traceEvents": []})
                    dst["traceEvents"].extend(t["traceEvents"])
            elif isinstance(payload, dict) and "events" in payload:
                dumps[rank] = payload
            else:
                print(f"trace_merge: skipping {path} (neither a chrome "
                      "trace, a request timeline, nor a flight dump)",
                      file=sys.stderr)
    return traces, dumps


def build_report(dumps: dict) -> dict:
    fr = _fr()
    events_by_rank = {r: d.get("collectives", d.get("events", []))
                      for r, d in dumps.items()}
    return {
        "schema": fr.REPORT_SCHEMA,
        "source": "trace_merge",
        "ranks": sorted(dumps),
        "reasons": {r: d.get("reason") for r, d in dumps.items()},
        "stalled_heartbeat_ranks": sorted(
            {r for d in dumps.values() for r in d.get("stalled_ranks", [])}),
        "desync": fr.desync_report(events_by_rank, world=sorted(dumps)),
        "straggler": fr.straggler_report(events_by_rank),
    }


def filter_request(merged: dict, trace_id: str) -> dict:
    """One request's flow out of a merged chrome trace: its spans
    (``args.trace_id`` match), its flow arrows (``id`` match) and the
    process-name metadata of the lanes it touched."""
    keep, pids = [], set()
    for e in merged.get("traceEvents", []):
        if (e.get("args") or {}).get("trace_id") == trace_id \
                or (e.get("cat") == "request" and e.get("id") == trace_id):
            keep.append(e)
            pids.add(e.get("pid"))
    meta = [e for e in merged.get("traceEvents", [])
            if e.get("ph") == "M" and e.get("pid") in pids]
    return {"traceEvents": meta + keep,
            "displayTimeUnit": merged.get("displayTimeUnit", "ms")}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="merge per-rank flight dumps / traces")
    ap.add_argument("inputs", nargs="+",
                    help="per-rank json files (globs ok)")
    ap.add_argument("--trace", help="write merged chrome trace here")
    ap.add_argument("--report", help="write cross-rank report here")
    ap.add_argument("--request", metavar="TRACE_ID",
                    help="filter --trace output to one request's flow")
    args = ap.parse_args(argv)
    if not args.trace and not args.report:
        ap.error("need --trace and/or --report")
    if args.request and not args.trace:
        ap.error("--request needs --trace (it filters the merged trace)")

    traces, dumps = load_inputs(args.inputs)
    fr = _fr()

    if args.trace:
        if not traces:
            print("trace_merge: no chrome traces among the inputs",
                  file=sys.stderr)
            return 2
        merged = fr.merge_chrome_traces(traces)
        if args.request:
            merged = filter_request(merged, args.request)
            if not any((e.get("args") or {}).get("trace_id")
                       == args.request for e in merged["traceEvents"]):
                print(f"trace_merge: no events carry trace_id "
                      f"{args.request!r}", file=sys.stderr)
                return 2
        with open(args.trace, "w") as f:
            json.dump(merged, f)
        print(f"trace_merge: {len(traces)} rank trace(s) -> {args.trace} "
              f"({len(merged['traceEvents'])} events)")

    if args.report:
        if not dumps:
            print("trace_merge: no flight dumps among the inputs",
                  file=sys.stderr)
            return 2
        report = build_report(dumps)
        with open(args.report, "w") as f:
            json.dump(report, f)
        stalled = report["desync"]["stalled"]
        if stalled:
            for s in stalled:
                print(f"trace_merge: DESYNC rank {s['rank']} never entered "
                      f"seq {s['missing_seq']} "
                      f"(op={s['op']}, last_seq={s['last_seq']})")
        else:
            print("trace_merge: no desync across "
                  f"{len(dumps)} rank dump(s)")
        print(f"trace_merge: report -> {args.report}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
