"""Merge per-rank flight-recorder dumps / chrome traces into one view.

Two input shapes, auto-detected per file:

* chrome traces (``{"traceEvents": [...]}``, e.g. the Profiler's
  ``worker_*.pt.trace.json`` per-rank exports) — merged into ONE trace
  with one pid per rank (``--trace out.json``);
* flight-recorder dumps (``flight_rank*.json``, schema
  ``paddle_flight_recorder/1``) — merged into a cross-rank
  desync/straggler report (``--report out.json``) that names the rank
  and collective seq id a hang is stuck on.

The rank of a file comes from its payload (dumps carry ``rank``) or
from a ``rank<N>`` substring in the filename, else its position.

Usage:
    python tools/trace_merge.py --trace merged.json rank*.trace.json
    python tools/trace_merge.py --report report.json flight_rank*.json
    python tools/trace_merge.py --report r.json --trace t.json <mixed...>
"""
from __future__ import annotations

import argparse
import glob
import importlib.util
import json
import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_FR = None


def _fr():
    """The flight_recorder module. It is stdlib-only, so load it straight
    from its file — the CLI must not drag in jax just to merge JSON."""
    global _FR
    if _FR is None:
        mod = sys.modules.get("paddle_tpu.profiler.flight_recorder")
        if mod is not None:              # already imported (tests)
            _FR = mod
        else:
            path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "paddle_tpu", "profiler",
                                "flight_recorder.py")
            spec = importlib.util.spec_from_file_location(
                "_flight_recorder_cli", path)
            _FR = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(_FR)
    return _FR


def _rank_of(path, payload, fallback):
    if isinstance(payload, dict) and isinstance(payload.get("rank"), int):
        return payload["rank"]
    m = re.search(r"rank[_-]?(\d+)", os.path.basename(path))
    return int(m.group(1)) if m else fallback


def load_inputs(paths):
    """Split the input files into ({rank: trace}, {rank: dump})."""
    traces, dumps = {}, {}
    idx = 0
    for pattern in paths:
        hits = sorted(glob.glob(pattern)) or [pattern]
        for path in hits:
            with open(path) as f:
                payload = json.load(f)
            rank = _rank_of(path, payload, idx)
            idx += 1
            if isinstance(payload, dict) and "traceEvents" in payload:
                traces[rank] = payload
            elif isinstance(payload, dict) and "events" in payload:
                dumps[rank] = payload
            else:
                print(f"trace_merge: skipping {path} (neither a chrome "
                      "trace nor a flight dump)", file=sys.stderr)
    return traces, dumps


def build_report(dumps: dict) -> dict:
    fr = _fr()
    events_by_rank = {r: d.get("collectives", d.get("events", []))
                      for r, d in dumps.items()}
    return {
        "schema": fr.REPORT_SCHEMA,
        "source": "trace_merge",
        "ranks": sorted(dumps),
        "reasons": {r: d.get("reason") for r, d in dumps.items()},
        "stalled_heartbeat_ranks": sorted(
            {r for d in dumps.values() for r in d.get("stalled_ranks", [])}),
        "desync": fr.desync_report(events_by_rank, world=sorted(dumps)),
        "straggler": fr.straggler_report(events_by_rank),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="merge per-rank flight dumps / traces")
    ap.add_argument("inputs", nargs="+",
                    help="per-rank json files (globs ok)")
    ap.add_argument("--trace", help="write merged chrome trace here")
    ap.add_argument("--report", help="write cross-rank report here")
    args = ap.parse_args(argv)
    if not args.trace and not args.report:
        ap.error("need --trace and/or --report")

    traces, dumps = load_inputs(args.inputs)
    fr = _fr()

    if args.trace:
        if not traces:
            print("trace_merge: no chrome traces among the inputs",
                  file=sys.stderr)
            return 2
        merged = fr.merge_chrome_traces(traces)
        with open(args.trace, "w") as f:
            json.dump(merged, f)
        print(f"trace_merge: {len(traces)} rank trace(s) -> {args.trace} "
              f"({len(merged['traceEvents'])} events)")

    if args.report:
        if not dumps:
            print("trace_merge: no flight dumps among the inputs",
                  file=sys.stderr)
            return 2
        report = build_report(dumps)
        with open(args.report, "w") as f:
            json.dump(report, f)
        stalled = report["desync"]["stalled"]
        if stalled:
            for s in stalled:
                print(f"trace_merge: DESYNC rank {s['rank']} never entered "
                      f"seq {s['missing_seq']} "
                      f"(op={s['op']}, last_seq={s['last_seq']})")
        else:
            print("trace_merge: no desync across "
                  f"{len(dumps)} rank dump(s)")
        print(f"trace_merge: report -> {args.report}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
